"""Sharded BSP engine: SPMD supersteps over a TPU device mesh.

The distributed design the reference implements with hash-sharded partition
managers + point-to-point actor messages + ack counting
(``Utils.scala:32-47`` sharding, ``EntityStorage`` sync protocol,
``AnalysisTask.scala:197-283`` coordinator) re-expressed the TPU way:

* The padded vertex space is range-partitioned over the mesh's ``vertices``
  axis (contiguous slices — not hash: keeps segment ids sorted per shard).
* Edges are materialised twice, partitioned by DST shard (for out-direction
  combine-at-destination) and by SRC shard (for in-direction) — the analogue
  of the reference's src-copy + ``SplitEdge`` dst-mirror, but immutable, so
  the entire ack/sync dance disappears.
* A superstep moves remote neighbour state over ICI by one of two routes,
  chosen per (graph, mesh) by measured exchange volume (``comm="auto"``):
  - **all_gather**: replicate the (small) per-vertex state along the vertex
    axis — best when most shards reference most of the graph (dense or tiny
    graphs, few shards).
  - **halo exchange**: at partition time each shard records exactly which
    REMOTE vertices its edges reference (the halo — the immutable analogue
    of the reference's ``SplitEdge`` dst-mirrors); each superstep exchanges
    only those rows via one ``all_to_all`` over ICI. O(halo) instead of
    O(|V|) bytes — the SURVEY §2.9 row-4 translation (point-to-point vertex
    messages → collective exchange of referenced remote state).
* Votes/quiescence are a ``psum`` — the reference's coordinator counting
  EndStep acks collapses into one collective (SURVEY §2.9).
* Batched windows ride a second mesh axis (``windows``) — window sweeps are
  embarrassingly parallel, so multi-chip scaling multiplies window throughput
  (the reference's analogue of sequence parallelism, SURVEY §5.7).
* Occurrence (temporal multigraph) programs — TaintTracking et al.
  (``EthereumTaintTracking.scala:93-127``) — shard exactly like deduplicated
  edges: the per-event ``occ_*`` arrays are scattered into dst-/src-
  partitioned blocks with per-occurrence times and props.
"""

from __future__ import annotations

import functools
import os
import threading
import time as _time
from collections import deque
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analysis.sanitizer import mesh_active
from ..core.snapshot import GraphView, INT64_MIN
from ..engine.bsp import _elem, _merge_aggs
from ..engine.program import Context, Edges, VertexProgram
from ..obs.trace import TRACER
from ..ops.segment import segment_combine

V_AXIS = "vertices"
W_AXIS = "windows"


def _metrics():
    """obs.metrics bundle, or None when prometheus isn't importable —
    collective telemetry must never make prometheus a hard dependency
    of the compute path."""
    try:
        from ..obs.metrics import METRICS

        return METRICS
    except Exception:
        return None


class CollectiveStats:
    """Process-wide accounting of what the cross-shard exchanges moved —
    the measured evidence ROADMAP item 3's sparse third collective route
    will be chosen against ("Sparse Allreduce": exchange only nonzero
    frontier slices; "Node Aware SpMV": aggregate intra-host before
    crossing DCN — both need per-route volume and skew numbers first).

    Thread-safe (concurrent mesh jobs dispatch from their own job
    threads); surfaced at ``/statusz`` and federated by ``/clusterz``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._routes: dict[tuple, dict] = {}
        self._skew: dict | None = None
        self._skew_builds = 0
        self._skew_refreshes = 0
        # route-chooser evidence: measured frontier density per
        # (algorithm, window-batch) key, and the decision log the
        # /statusz route table renders. Densities come from ALLGATHERED
        # per-process counts, so every process records identical history
        # — the chooser staying SPMD-uniform depends on it (COMM.md)
        self._frontier: dict[str, deque] = {}
        self._route_log: deque = deque(maxlen=64)
        self._route_counts: dict[tuple, int] = {}

    def note_partition(self, skew: dict) -> None:
        """Record the latest partition build's per-shard skew histogram
        (built at ``partition_view`` time — rebuilds overwrite)."""
        with self._lock:
            self._skew = skew
            self._skew_builds += 1

    def note_exchange(self, route: str, direction: str, *, rows: int,
                      bytes_: int, seconds: float, supersteps: int,
                      barrier_wait: float = 0.0,
                      async_dispatch: bool = False) -> None:
        """One dispatch's exchange accounting. ``rows``/``bytes_`` are
        totals over devices and (known) supersteps; async dispatches
        can't know their superstep count host-side and account exactly
        one superstep, counted separately so the undercount is visible."""
        with self._lock:
            d = self._routes.setdefault((route, direction), {
                "dispatches": 0, "supersteps": 0, "rows": 0, "bytes": 0,
                "seconds": 0.0, "barrier_wait_seconds": 0.0,
                "async_dispatches": 0})
            d["dispatches"] += 1
            d["supersteps"] += int(supersteps)
            d["rows"] += int(rows)
            d["bytes"] += int(bytes_)
            d["seconds"] += float(seconds)
            d["barrier_wait_seconds"] += float(barrier_wait)
            if async_dispatch:
                d["async_dispatches"] += 1
        m = _metrics()
        if m is not None:
            m.collective_seconds.labels(route, direction).inc(
                max(0.0, float(seconds)))
            m.collective_bytes.labels(route, direction).inc(
                max(0, int(bytes_)))
            m.collective_rows.labels(route, direction).inc(
                max(0, int(rows)))
            if barrier_wait > 0.0:
                m.collective_barrier_wait.labels(route).inc(
                    float(barrier_wait))

    def note_skew_refresh(self, skew: dict) -> None:
        """A post-ingest sampled skew recompute (NOT a partition build):
        replaces the published histogram so the route chooser and the
        advisor's shard-skew rule never read day-1 skew after a large
        ingest suffix shifted the load (docs/COMM.md)."""
        with self._lock:
            self._skew = skew
            self._skew_refreshes += 1

    def note_route_decision(self, decision: dict) -> None:
        """One dispatch's route-chooser verdict + evidence — the
        ``/statusz`` route table's feed (journaled by the dispatcher)."""
        algo = str(decision.get("algorithm", "?"))
        route = str(decision.get("route", "?"))
        with self._lock:
            self._route_log.append(dict(decision))
            key = (algo, route)
            self._route_counts[key] = self._route_counts.get(key, 0) + 1
        m = _metrics()
        if m is not None:
            m.route_decisions.labels(algo, route).inc()

    def note_frontier(self, key: str, density: float,
                      supersteps: int) -> None:
        """Measured mean frontier density of one sparse dispatch, keyed
        by (algorithm, window-batch) — the chooser's crossover input."""
        with self._lock:
            dq = self._frontier.setdefault(key, deque(maxlen=32))
            dq.append((float(density), int(supersteps)))

    def frontier_hint(self, key: str) -> float | None:
        """Mean measured frontier density for ``key`` (None = no
        history; the chooser then uses its cold-start prior)."""
        with self._lock:
            dq = self._frontier.get(key)
            if not dq:
                return None
            return sum(d for d, _ in dq) / len(dq)

    def snapshot(self) -> dict:
        with self._lock:
            routes = {f"{r}/{d}": dict(v)
                      for (r, d), v in sorted(self._routes.items())}
            skew = dict(self._skew) if self._skew else None
            builds = self._skew_builds
            refreshes = self._skew_refreshes
            density = {k: round(sum(d for d, _ in dq) / len(dq), 6)
                       for k, dq in sorted(self._frontier.items()) if dq}
            table = {
                "counts": {f"{a}/{r}": n for (a, r), n
                           in sorted(self._route_counts.items())},
                "recent": [dict(d) for d in list(self._route_log)[-8:]],
            }
        for v in routes.values():
            v["seconds"] = round(v["seconds"], 6)
            v["barrier_wait_seconds"] = round(
                v["barrier_wait_seconds"], 6)
        return {"routes": routes, "skew": skew, "skew_builds": builds,
                "skew_refreshes": refreshes,
                "frontier_density": density, "route_table": table}

    def clear(self) -> None:
        with self._lock:
            self._routes.clear()
            self._skew = None
            self._skew_builds = 0
            self._skew_refreshes = 0
            self._frontier.clear()
            self._route_log.clear()
            self._route_counts.clear()


#: process-wide collective accounting every mesh dispatch records into
COLLECTIVES = CollectiveStats()


def shard_skew(**kinds) -> dict:
    """Per-shard row-count skew summary: for each named kind (an array of
    per-shard counts), the per-shard histogram plus max/mean — the
    power-law imbalance signal. ``skew`` 1.0 = perfectly balanced."""
    out = {}
    for kind, arr in kinds.items():
        a = np.asarray(arr, np.float64).reshape(-1)
        mean = float(a.mean()) if a.size else 0.0
        mx = float(a.max()) if a.size else 0.0
        out[kind] = {
            "per_shard": [int(x) for x in a],
            "max": int(mx),
            "mean": round(mean, 2),
            "skew": round(mx / mean, 4) if mean > 0 else 1.0,
        }
    return out


def note_partition_skew(skew: dict) -> None:
    """Publish one partition build's skew histogram: COLLECTIVES (the
    /statusz / /clusterz surface), the prometheus gauges/histograms, and
    a flight-recorder instant — shared by ``partition_view`` and the
    static ``ShardedSweep`` build."""
    COLLECTIVES.note_partition(skew)
    m = _metrics()
    if m is not None:
        for kind, s in skew.items():
            m.partition_skew.labels(kind).set(s["skew"])
            for rows in s["per_shard"]:
                m.shard_rows.labels(kind).observe(float(rows))
    if TRACER.enabled:
        TRACER.instant("comm.partition",
                       process=TRACER.process_index,
                       **{f"{k}_skew": v["skew"] for k, v in skew.items()})


def sampled_skew(sv, max_cols: int = 1 << 16) -> dict:
    """Cheap post-ingest recompute of the per-shard edge skew from the
    CURRENT block masks (the partition-time histogram goes stale the
    moment a large ingest suffix shifts the load — the amortised sweep
    path never rebuilds its partition). Blocks wider than ``max_cols``
    are column-sampled at a deterministic stride and scaled back up; the
    static halo slot histogram is carried over unchanged (halo capacity
    does not move after the build)."""
    def counts(mask):
        m = mask.shape[1]
        step = max(1, m // max_cols)
        c = np.count_nonzero(mask[:, ::step], axis=1).astype(np.float64)
        return c * step

    kinds = {"edges_dst": counts(sv.d_mask), "edges_src": counts(sv.s_mask)}
    skew = shard_skew(**kinds)
    if sv.skew:
        for kind in ("halo_dst", "halo_src"):
            if kind in sv.skew:
                skew[kind] = dict(sv.skew[kind])
    return skew


def refresh_partition_skew(sv) -> dict:
    """Recompute + republish the skew of an EXISTING partition from live
    masks (``sampled_skew``) and stamp it onto the sharded view, so every
    downstream reader — the route chooser's evidence, the advisor's
    shard-skew rule, the ``/statusz`` gauges — sees post-ingest load, not
    the day-1 histogram. Counted separately from partition builds."""
    skew = sampled_skew(sv)
    sv.skew = skew
    COLLECTIVES.note_skew_refresh(skew)
    m = _metrics()
    if m is not None:
        for kind, s in skew.items():
            m.partition_skew.labels(kind).set(s["skew"])
    if TRACER.enabled:
        TRACER.instant("comm.skew_refresh",
                       process=TRACER.process_index,
                       **{f"{k}_skew": v["skew"] for k, v in skew.items()})
    return skew


#: comm routes a dispatch can take (docs/COMM.md route catalogue)
COMM_ROUTES = ("halo", "all_gather", "sparse")


def _dense_auto(sv, view, program, S: int) -> str:
    """The pre-sparse auto rule, unchanged: halo wins when the referenced
    remote rows are fewer than the remote rows all_gather would replicate
    (n_pad - n_loc per device); ties go to all_gather, whose single
    collective schedules better."""
    return ("halo" if S > 1
            and sv.halo_rows(program.direction) < view.n_pad - sv.n_loc
            else "all_gather")


def choose_route(program, view, sv, mesh, requested: str, k: int,
                 multi: bool, *, env: str | None = None,
                 density_hint: float | None = None) -> dict:
    """Measured-driven comm-route decision for one dispatch. Returns the
    decision record (route + evidence) the dispatcher publishes as a
    ``comm.route`` instant, a journal record and a /statusz route-table
    row.

    SPMD-uniformity (the RT012 pragma-free design): every decision input
    is identical on every process by construction — shapes and halo/pad
    sizes come from the replicated partition build, ``multi`` from the
    mesh's global device list, skew from data-replicated ingestion, and
    frontier-density history from ALLGATHERED per-process counts
    (``CollectiveStats.note_frontier`` records the global density).
    Per-process measurements (exchange seconds, barrier wait) are
    carried as *evidence only* and never read by the decision.

    ``env``/``density_hint`` override the environment knob and the
    recorded history for decision-table tests."""
    from . import frontier as _frontier

    if env is None:
        env = os.environ.get("RTPU_COMM_ROUTE", "auto").strip().lower()
    env = env or "auto"
    env_valid = env in COMM_ROUTES + ("auto",)
    S = mesh.shape[V_AXIS]
    label = program.cost_label
    key = f"{label}/k{k}"
    eligible = _frontier.supported(program)
    if density_hint is None:
        density_hint = COLLECTIVES.frontier_hint(key)
    measured = density_hint is not None
    density = _frontier.PRIOR_DENSITY if density_hint is None else density_hint

    # per-superstep byte estimates (the crossover model, docs/COMM.md):
    # dense routes replicate rows to every device each superstep; sparse
    # ships one (index, value) slot per globally-changed row, floored at
    # the bucket length each participating process pads to
    item = 4          # eligible state leaves are i32 labels / f32 dists
    slot = 8 + item   # i64 flat index + value
    n_dev = int(mesh.devices.size)
    n_procs = len({d.process_index for d in mesh.devices.flat})
    est = {
        "all_gather": (view.n_pad - sv.n_loc) * k * item * n_dev,
        "halo": sv.halo_rows(program.direction) * k * item * n_dev,
        "sparse": max(density * k * view.n_pad * slot,
                      _partition_floor() * n_procs * slot),
    }
    dense_pick = _dense_auto(sv, view, program, S)

    route = requested
    reason = "explicit comm= argument"
    if requested == "auto":
        if env != "auto" and env_valid:
            route = env
            reason = "forced by RTPU_COMM_ROUTE"
        elif not env_valid:
            route = "auto"
            reason = f"invalid RTPU_COMM_ROUTE={env!r} ignored"
        else:
            route = "auto"
            reason = "auto"
    if route == "sparse" and not eligible:
        if requested == "sparse":
            raise ValueError(
                f"comm='sparse' requires the monotone_min contract; "
                f"{type(program).__name__} does not declare it")
        route = dense_pick
        reason = ("RTPU_COMM_ROUTE=sparse ignored: "
                  f"{label} is not monotone_min — dense fallback")
    if route == "auto":
        if eligible and multi and est["sparse"] < min(est["all_gather"],
                                                     est["halo"]):
            route = "sparse"
            reason = ("measured density" if measured else "prior density") \
                + " puts sparse below both dense routes"
        else:
            route = dense_pick
            if not eligible:
                reason = "program not monotone_min: dense volume rule"
            elif not multi:
                reason = "single-process mesh: dense volume rule"
            else:
                reason = "frontier density above crossover: dense volume rule"

    skew_max = 0.0
    if sv.skew:
        skew_max = max(float(s.get("skew", 1.0)) for s in sv.skew.values())
    # evidence-only route history (bytes are shape-derived and uniform;
    # seconds/barrier_wait are per-process and deliberately NOT inputs)
    hist = {}
    snap = COLLECTIVES.snapshot()["routes"]
    for rk, v in snap.items():
        r = rk.split("/")[0]
        h = hist.setdefault(r, {"bytes": 0, "supersteps": 0,
                                "barrier_wait_seconds": 0.0})
        h["bytes"] += v["bytes"]
        h["supersteps"] += v["supersteps"]
        h["barrier_wait_seconds"] = round(
            h["barrier_wait_seconds"] + v["barrier_wait_seconds"], 6)
    return {
        "algorithm": label,
        "key": key,
        "requested": requested,
        "env": env if env != "auto" else None,
        "route": route,
        "reason": reason,
        "eligible": eligible,
        "evidence": {
            "n_pad": int(view.n_pad),
            "k": int(k),
            "shards": int(S),
            "processes": int(n_procs),
            "multi": bool(multi),
            "density": round(float(density), 6),
            "density_measured": measured,
            "est_bytes_per_superstep": {r: int(b) for r, b in est.items()},
            "skew_max": round(skew_max, 4),
            "route_history": hist,
        },
    }


def _partition_floor() -> int:
    from ..ops.partition import sparse_bucket_floor

    return sparse_bucket_floor()


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` with vma checking on jax >= 0.6; the experimental
    ``shard_map`` (no vma system — the explicit ``vary()``/vma-seeding
    promotions are no-ops there, and ``check_rep`` is off because the
    halting psums intentionally mix replicated and varying operands) on
    older jax. One shim so both parallel runners track the API move."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=True)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def make_mesh(n_vertex_shards: int | None = None, n_window_shards: int = 1,
              devices=None) -> Mesh:
    """Build a (windows, vertices) mesh. Defaults to all devices on the
    vertex axis — the common layout for one big graph."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    total = devices.size
    if n_vertex_shards is None:
        n_vertex_shards = total // n_window_shards
    assert n_vertex_shards * n_window_shards == total, (
        f"{n_vertex_shards}x{n_window_shards} != {total} devices")
    return Mesh(devices.reshape(n_window_shards, n_vertex_shards),
                (W_AXIS, V_AXIS))


@dataclass
class ShardedView:
    """Host-side partitioned snapshot: leading axis = vertex shard."""

    n_shards: int
    n_loc: int                 # vertices per shard
    m_loc_d: int               # padded edges per shard (dst partition)
    m_loc_s: int               # padded edges per shard (src partition)
    vids: np.ndarray           # i64[S, n_loc]
    v_mask: np.ndarray         # bool[S, n_loc]
    v_latest: np.ndarray       # i64[S, n_loc]
    v_first: np.ndarray        # i64[S, n_loc]
    # dst partition: combine-at-dst; src index is GLOBAL (gathered state)
    d_src_g: np.ndarray        # i32[S, m_loc_d]
    d_dst_l: np.ndarray        # i32[S, m_loc_d]  local, sorted, pad n_loc-1
    d_mask: np.ndarray         # bool[S, m_loc_d]
    d_time: np.ndarray         # i64[S, m_loc_d]
    d_first: np.ndarray
    # src partition: combine-at-src; dst index is GLOBAL
    s_dst_g: np.ndarray        # i32[S, m_loc_s]
    s_src_l: np.ndarray        # i32[S, m_loc_s]  local, sorted, pad n_loc-1
    s_mask: np.ndarray
    s_time: np.ndarray
    s_first: np.ndarray
    d_props: dict              # name -> f32[S, m_loc_d]
    s_props: dict
    view: GraphView
    occurrences: bool = False  # blocks hold occ_* (multigraph) rows
    # halo structures (one per partition direction): h_* is the per-
    # (requester, owner) slot capacity; *_h remaps the global ref array into
    # [local | halo] space [0, n_loc + S*h); *_send[S, S*h] is each owner
    # device's all_to_all send page (local rows grouped by requester).
    h_d: int = 0
    d_src_h: np.ndarray | None = None   # i32[S, m_loc_d]
    d_send: np.ndarray | None = None    # i32[S, S*h_d]
    h_s: int = 0
    s_dst_h: np.ndarray | None = None
    s_send: np.ndarray | None = None
    #: per-shard degree/halo row-count histogram built at partition time
    #: (``shard_skew`` output) — the power-law imbalance evidence
    skew: dict | None = None

    def halo_rows(self, direction: str) -> int:
        """Rows exchanged per device per superstep on the halo path (vs
        ``view.n_pad - n_loc`` received per device for all_gather)."""
        rows = 0
        if direction in ("out", "both"):
            rows += self.n_shards * self.h_d
        if direction in ("in", "both"):
            rows += self.n_shards * self.h_s
        return rows


def _pow2(n: int) -> int:
    return 8 if n <= 8 else 1 << int(np.ceil(np.log2(n)))


def _build_halo(idx_g: np.ndarray, n_loc: int, S: int):
    """Halo layout for one partition direction.

    ``idx_g[S, m_loc]`` holds GLOBAL vertex refs per shard. Returns
    ``(h, idx_h, send, halo_counts)``: per-(requester, owner) slot
    capacity ``h``; ``idx_h[S, m_loc]`` remapping each ref into the
    shard's extended space — local row for own vertices,
    ``n_loc + owner*h + slot`` for remote ones; ``send[S, S*h]`` where
    row ``o`` is owner-device o's all_to_all send page: chunk ``r`` lists
    the local rows requester ``r`` referenced (sorted unique; slot order
    matches the requester's remap); ``halo_counts[S]`` counts each
    requester's unique remote refs (the per-shard halo-skew signal)."""
    idx_h = np.zeros(idx_g.shape, np.int32)
    halo_counts = np.zeros(idx_g.shape[0], np.int64)
    uniq = []  # (requester, u_owner[], u_g[], slot[])
    maxcnt = 1
    for sh in range(S):
        g = idx_g[sh].astype(np.int64)
        owner = g // n_loc
        local = owner == sh
        idx_h[sh, local] = (g[local] - sh * n_loc).astype(np.int32)
        rem = np.flatnonzero(~local)
        if len(rem) == 0:
            continue
        go, oo = g[rem], owner[rem]
        order = np.lexsort((go, oo))
        gs, os_ = go[order], oo[order]
        new = np.ones(len(gs), bool)
        new[1:] = (gs[1:] != gs[:-1]) | (os_[1:] != os_[:-1])
        uid = np.cumsum(new) - 1                      # unique rank per row
        u_owner = os_[new]
        u_g = gs[new]
        # slot within owner group = unique rank − rank at owner's first unique
        o_change = np.ones(len(u_owner), bool)
        o_change[1:] = u_owner[1:] != u_owner[:-1]
        arange_u = np.arange(len(u_g))
        base = np.maximum.accumulate(np.where(o_change, arange_u, 0))
        slot = (arange_u - base).astype(np.int64)
        maxcnt = max(maxcnt, int(slot.max()) + 1)
        halo_counts[sh] = len(u_g)
        # remote-row remap happens in the second pass (slots need final h)
        uniq.append((sh, u_owner, u_g, slot, rem[order], uid))
    h = _pow2(maxcnt)
    send = np.zeros((S, S * h), np.int32)
    for sh, u_owner, u_g, slot, rows, uid in uniq:
        idx_h[sh, rows] = (n_loc + u_owner[uid] * h + slot[uid]).astype(np.int32)
        send[u_owner, sh * h + slot] = (u_g - u_owner * n_loc).astype(np.int32)
    return h, idx_h, send, halo_counts


# build counter — the amortisation witness: range sweeps that re-partition
# per hop (the round-3 regression class) show up as increments here.
# Bumps go through note_partition_build(): concurrent mesh jobs each
# build partitions on their own job thread, and an unguarded += loses
# counts exactly when the witness matters (rtpulint RT010)
PARTITION_BUILDS = 0
_BUILDS_LOCK = threading.Lock()


def note_partition_build() -> None:
    global PARTITION_BUILDS
    with _BUILDS_LOCK:
        PARTITION_BUILDS += 1


def partition_view(view: GraphView, n_shards: int,
                   edge_props: tuple = (),
                   occurrences: bool = False) -> ShardedView:
    """Range-partition the padded vertex space into contiguous shards and
    scatter edges into per-shard blocks (dst- and src-partitioned), plus the
    halo exchange layout. With ``occurrences=True`` the blocks hold the
    multigraph occurrence rows (per-event times/props) instead of the
    deduplicated edges."""
    assert view.n_pad % n_shards == 0, (
        f"vertex shard count {n_shards} must divide the padded vertex count "
        f"{view.n_pad} (pad buckets are powers of two; use a power-of-two "
        f"vertex-axis size)")
    note_partition_build()
    n_loc = view.n_pad // n_shards
    S = n_shards

    if occurrences:
        if view.occ_src is None:
            raise ValueError("program needs occurrences: build the view "
                             "with include_occurrences=True")
        act = view.occ_mask
        esrc = view.occ_src[act].astype(np.int64)
        edst = view.occ_dst[act].astype(np.int64)
        etime = view.occ_time[act]
        efirst = view.occ_time[act]
        props = {k: view.occ_prop(k)[act] for k in edge_props}
    else:
        act = view.e_mask
        esrc = view.e_src[act].astype(np.int64)
        edst = view.e_dst[act].astype(np.int64)
        etime = view.e_latest_time[act]
        efirst = view.e_first_time[act]
        props = {k: view.edge_prop(k)[act] for k in edge_props}

    def _partition(owner_of, local_of, global_of):
        owner = owner_of // n_loc
        order = np.lexsort((local_of, owner))
        counts = np.bincount(owner, minlength=S)
        shard_counts.append(np.asarray(counts[:S], np.int64))
        m_loc = _pow2(int(counts.max()) if len(counts) else 0)
        idx_g = np.full((S, m_loc), view.n_pad - 1, np.int32)
        idx_l = np.full((S, m_loc), n_loc - 1, np.int32)
        mask = np.zeros((S, m_loc), bool)
        tarr = np.full((S, m_loc), INT64_MIN, np.int64)
        farr = np.full((S, m_loc), INT64_MIN, np.int64)
        parr = {k: np.zeros((S, m_loc), np.float32) for k in props}
        off = 0
        for sh in range(S):
            c = int(counts[sh]) if sh < len(counts) else 0
            rows = order[off : off + c]
            off += c
            idx_g[sh, :c] = global_of[rows]
            idx_l[sh, :c] = (owner_of[rows] - sh * n_loc)
            mask[sh, :c] = True
            tarr[sh, :c] = etime[rows]
            farr[sh, :c] = efirst[rows]
            for kk in props:
                parr[kk][sh, :c] = props[kk][rows]
        return m_loc, idx_g, idx_l, mask, tarr, farr, parr

    shard_counts: list = []   # filled by _partition (dst then src)
    m_loc_d, d_src_g, d_dst_l, d_mask, d_time, d_first, d_props = _partition(
        edst, edst % n_loc, esrc)
    m_loc_s, s_dst_g, s_src_l, s_mask, s_time, s_first, s_props = _partition(
        esrc, esrc % n_loc, edst)

    h_d, d_src_h, d_send, halo_d = _build_halo(d_src_g, n_loc, S)
    h_s, s_dst_h, s_send, halo_s = _build_halo(s_dst_g, n_loc, S)

    # per-shard degree/halo histogram — the partition-time skew evidence
    # (power-law graphs concentrate edges and halo refs on few shards)
    skew = shard_skew(edges_dst=shard_counts[0], edges_src=shard_counts[1],
                      halo_dst=halo_d, halo_src=halo_s)
    note_partition_skew(skew)

    rs = lambda a: a.reshape(S, n_loc)
    return ShardedView(
        n_shards=S, n_loc=n_loc, m_loc_d=m_loc_d, m_loc_s=m_loc_s,
        vids=rs(view.vids), v_mask=rs(view.v_mask),
        v_latest=rs(view.v_latest_time), v_first=rs(view.v_first_time),
        d_src_g=d_src_g, d_dst_l=d_dst_l, d_mask=d_mask,
        d_time=d_time, d_first=d_first,
        s_dst_g=s_dst_g, s_src_l=s_src_l, s_mask=s_mask,
        s_time=s_time, s_first=s_first,
        d_props=d_props, s_props=s_props, view=view,
        occurrences=occurrences,
        h_d=h_d, d_src_h=d_src_h, d_send=d_send,
        h_s=h_s, s_dst_h=s_dst_h, s_send=s_send,
        skew=skew,
    )


@functools.lru_cache(maxsize=128)
def _sharded_runner(program: VertexProgram, mesh: Mesh, n_loc: int,
                    m_loc_d: int, m_loc_s: int, k_loc: int, n_pad: int,
                    prop_keys: tuple, comm: str = "all_gather",
                    h_d: int = 0, h_s: int = 0):
    """Compile one SPMD program for (algorithm, shapes, mesh, comm route)."""
    reduce_axes = (W_AXIS, V_AXIS)
    S_v = mesh.shape[V_AXIS]

    def gather_state(state_loc):
        # state leaves are [k_loc, n_loc, ...]: the vertex axis is axis 1
        # (axis 0 is the local window batch) — tiled gather concatenates the
        # contiguous range partitions back into global vertex order
        return jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, V_AXIS, axis=1, tiled=True),
            state_loc)

    def exchange_halo(state_loc, send_idx):
        # halo route: each device ships ONLY the rows its peers reference.
        # send_idx i32[S*h]: chunk r = local rows requester r wants; one
        # tiled all_to_all swaps chunks so chunk o of the result is what
        # owner o sent us — laid out to match the *_h remaps. Result leaves
        # are the extended space [k_loc, n_loc + S*h, ...] (own rows first).
        def leaf(a):
            send = jnp.take(a, send_idx, axis=1)
            recv = jax.lax.all_to_all(
                send, V_AXIS, split_axis=1, concat_axis=1, tiled=True)
            return jnp.concatenate([a, recv], axis=1)
        return jax.tree_util.tree_map(leaf, state_loc)

    def device_fn(v_mask, vids, v_latest, v_first,
                  d_src_g, d_dst_l, d_mask, d_time, d_first,
                  s_dst_g, s_src_l, s_mask, s_time, s_first,
                  halo, d_props, s_props, vprops, time, windows):
        # shapes (per device): v_mask [Kl, n_loc]; d_* [m_loc_d] / masks
        # [Kl, m_loc_d]; windows [Kl]
        v_off = jax.lax.axis_index(V_AXIS).astype(jnp.int32) * n_loc

        # Flat window-major layout: the window batch is ONE graph of
        # k_loc*n_loc local vertices, per-window segment ids offset by
        # kk*n_loc. One scatter for all windows — and no vmapped scatter
        # inside the superstep while_loop, the shape that miscompiles on
        # the TPU backend when the loop condition reads carried state
        # (see engine/bsp.py make_runner).
        woffs_loc = (jnp.arange(k_loc, dtype=jnp.int32) * n_loc)[:, None]
        fl_d_dst = (d_dst_l[None, :] + woffs_loc).reshape(-1)  # sorted/blk
        fl_s_src = (s_src_l[None, :] + woffs_loc).reshape(-1)  # sorted/blk
        if comm == "halo":
            # gather indices live in each shard's [local | halo] space
            ext_d = n_loc + S_v * h_d
            ext_s = n_loc + S_v * h_s
            woffs_d = (jnp.arange(k_loc, dtype=jnp.int32) * ext_d)[:, None]
            woffs_s = (jnp.arange(k_loc, dtype=jnp.int32) * ext_s)[:, None]
            fl_d_src = (halo["d_src_h"][None, :] + woffs_d).reshape(-1)
            fl_s_dst = (halo["s_dst_h"][None, :] + woffs_s).reshape(-1)
        else:
            woffs_pad = (jnp.arange(k_loc, dtype=jnp.int32) * n_pad)[:, None]
            fl_d_src = (d_src_g[None, :] + woffs_pad).reshape(-1)
            fl_s_dst = (s_dst_g[None, :] + woffs_pad).reshape(-1)
        dm_flat = d_mask.reshape(-1)
        sm_flat = s_mask.reshape(-1)

        def tile_d(a):
            return jnp.broadcast_to(a[None, :], (k_loc,) + a.shape).reshape(
                (k_loc * m_loc_d,) + a.shape[1:])

        def tile_s(a):
            return jnp.broadcast_to(a[None, :], (k_loc,) + a.shape).reshape(
                (k_loc * m_loc_s,) + a.shape[1:])

        def combine_flat(tree_flat, ids, msk):
            if program.combiner == "custom":
                agg = program.exchange(tree_flat, ids, k_loc * n_loc, msk)
                return jax.tree_util.tree_map(
                    lambda a: a.reshape((k_loc, n_loc) + a.shape[1:]), agg)

            def leaf(x):
                out = segment_combine(x, ids, k_loc * n_loc, program.combiner,
                                      msk, indices_are_sorted=True)
                return out.reshape((k_loc, n_loc) + x.shape[1:])
            return jax.tree_util.tree_map(leaf, tree_flat)

        in_deg = segment_combine(
            jnp.ones((k_loc * m_loc_d,), jnp.int32), fl_d_dst,
            k_loc * n_loc, "sum", dm_flat, True).reshape(k_loc, n_loc)
        out_deg = segment_combine(
            jnp.ones((k_loc * m_loc_s,), jnp.int32), fl_s_src,
            k_loc * n_loc, "sum", sm_flat, True).reshape(k_loc, n_loc)

        def mk_ctx(kk, step):
            n_act = jnp.sum(v_mask[kk].astype(jnp.int32))
            n_act = jax.lax.psum(n_act, V_AXIS)
            return Context(
                n=n_loc, time=time, window=windows[kk], v_mask=v_mask[kk],
                vids=vids, v_latest_time=v_latest, v_first_time=v_first,
                out_deg=out_deg[kk], in_deg=in_deg[kk], n_active=n_act,
                step=step, vprops=vprops, v_offset=v_off, axis_name=V_AXIS,
            )

        def init_k(kk):
            return program.init(mk_ctx(kk, jnp.int32(0)))

        state0 = jax.vmap(init_k)(jnp.arange(k_loc))

        def gather_flat(st_pool, ids, width):
            return jax.tree_util.tree_map(
                lambda a: a.reshape((k_loc * width,) + a.shape[2:])[ids],
                st_pool)

        def step_all(st, step):
            if comm == "halo":
                pool_d = lambda: exchange_halo(st, halo["d_send"])
                pool_s = lambda: exchange_halo(st, halo["s_send"])
                width_d, width_s = n_loc + S_v * h_d, n_loc + S_v * h_s
            else:
                st_full = gather_state(st)  # [k_loc, n_pad, ...]
                pool_d = pool_s = lambda: st_full
                width_d = width_s = n_pad
            agg = None
            if program.direction in ("out", "both"):
                # Edges contract: src/dst are GLOBAL padded indices
                edges = Edges(src=tile_d(d_src_g), dst=tile_d(d_dst_l) + v_off,
                              mask=dm_flat, time=tile_d(d_time),
                              first_time=tile_d(d_first),
                              props=jax.tree_util.tree_map(tile_d, d_props),
                              step=step)
                payload = program.message(
                    gather_flat(pool_d(), fl_d_src, width_d), edges)
                agg = combine_flat(payload, fl_d_dst, dm_flat)
            if program.direction in ("in", "both"):
                edges = Edges(src=tile_s(s_src_l) + v_off, dst=tile_s(s_dst_g),
                              mask=sm_flat, time=tile_s(s_time),
                              first_time=tile_s(s_first),
                              props=jax.tree_util.tree_map(tile_s, s_props),
                              step=step)
                payload = program.message(
                    gather_flat(pool_s(), fl_s_dst, width_s), edges)
                agg_in = combine_flat(payload, fl_s_src, sm_flat)
                agg = agg_in if agg is None else _merge_aggs(
                    program.combiner, agg, agg_in)

            def upd_k(kk, stk, aggk):
                new_st, votes = program.update(stk, aggk, mk_ctx(kk, step))
                # local vote only — caller makes it global (psum over shards)
                unhalted = jnp.sum((~(votes | ~v_mask[kk])).astype(jnp.int32))
                return new_st, unhalted

            return jax.vmap(upd_k, in_axes=(0, 0, 0))(
                jnp.arange(k_loc), st, agg)


        def vary(x):
            """Promote x to varying over exactly the mesh axes it is missing
            (no-op when already fully varying) — shard_map's check_vma
            requires explicit promotion of shard-invariant values. Pre-vma
            jax (< 0.6) has no typeof/pcast and needs no promotion."""
            if not hasattr(jax, "typeof") or not hasattr(jax.lax, "pcast"):
                return x
            missing = tuple(a for a in (W_AXIS, V_AXIS)
                            if a not in jax.typeof(x).vma)
            return jax.lax.pcast(x, missing, to="varying") if missing else x

        if program.max_steps > 0:
            def cond(carry):
                step, _, halted = carry
                # halted is per-window and identical on every vertex shard
                # (derived from a psum over V); any unhalted window anywhere
                # keeps every device stepping — SPMD-uniform condition.
                # vary() marks the (possibly vertex-invariant) count varying
                # so the full-mesh psum type-checks under check_vma; summing
                # S_v identical copies only scales the >0 test.
                unhalted = vary(jnp.sum((~halted).astype(jnp.int32)))
                unhalted = jax.lax.psum(unhalted, reduce_axes)
                return (step < program.max_steps) & (unhalted > 0)

            def body(carry):
                step, st, halted = carry
                new_st, unhalted_local = step_all(st, step)
                # per-window GLOBAL quiescence: a window halts only when no
                # shard changed state — freezing must never be shard-local,
                # or a converged shard would stop receiving neighbours'
                # updates. (The reference's coordinator quiescence check,
                # AnalysisTask.scala:237-283, as one psum.)
                unhalted_global = jax.lax.psum(unhalted_local, V_AXIS)
                new_halt = unhalted_global == 0
                st = jax.tree_util.tree_map(
                    lambda old, new: jnp.where(
                        halted.reshape((k_loc,) + (1,) * (new.ndim - 1)),
                        old, new),
                    st, new_st)
                return step + 1, st, halted | new_halt

            # The loop body makes every carry leaf varying over the whole
            # mesh (state via the exchange, halted via the psum), but leaves
            # a program's init() built from constants start invariant —
            # promote each initial leaf to varying over exactly the axes it
            # is missing so the while_loop carry is type-stable.
            halted0 = vary(jnp.zeros((k_loc,), bool))
            state0 = jax.tree_util.tree_map(vary, state0)
            steps, state, _ = jax.lax.while_loop(
                cond, body, (jnp.int32(0), state0, halted0))
        else:
            steps, state = jnp.int32(0), state0

        def fin_k(kk, st):
            return program.finalize(st, mk_ctx(kk, steps))

        result = jax.vmap(fin_k, in_axes=(0, 0))(jnp.arange(k_loc), state)
        return result, steps

    # specs: window-sharded leading axis (if any), vertex-sharded second
    kv = P(W_AXIS, V_AXIS)       # [K, S, ...]: windows on W, shards on V
    v = P(V_AXIS)                # [S, ...]: shard axis 0, replicated over W
    in_specs = (
        kv,            # v_mask [K, S, n_loc]
        v, v, v,       # vids, v_latest, v_first [S, n_loc]
        v, v, kv, v, v,        # d_src_g, d_dst_l, d_mask[K,S,m], d_time, d_first
        v, v, kv, v, v,        # s_dst_g, s_src_l, s_mask, s_time, s_first
        v,             # halo dict (leaves [S, m_loc] / [S, S*h])
        v, v, v,       # edge/vertex prop dicts (leaves [S, m_loc] / [S, n_loc])
        P(),           # time scalar
        P(W_AXIS),     # windows [K]
    )
    out_specs = (P(W_AXIS, V_AXIS), P())

    def squeeze_fn(v_mask, vids, v_latest, v_first,
                   d_src_g, d_dst_l, d_mask, d_time, d_first,
                   s_dst_g, s_src_l, s_mask, s_time, s_first,
                   halo, d_props, s_props, vprops, time, windows):
        # strip the sharded block axes: [Kl, 1, ...] -> [Kl, ...]; [1, ...] -> [...]
        sq_kv = lambda a: a.reshape((a.shape[0],) + a.shape[2:])
        sq_v = lambda a: a.reshape(a.shape[1:])
        result, steps = device_fn(
            sq_kv(v_mask), sq_v(vids), sq_v(v_latest), sq_v(v_first),
            sq_v(d_src_g), sq_v(d_dst_l), sq_kv(d_mask), sq_v(d_time), sq_v(d_first),
            sq_v(s_dst_g), sq_v(s_src_l), sq_kv(s_mask), sq_v(s_time), sq_v(s_first),
            jax.tree_util.tree_map(sq_v, halo),
            jax.tree_util.tree_map(sq_v, d_props),
            jax.tree_util.tree_map(sq_v, s_props),
            jax.tree_util.tree_map(sq_v, vprops),
            time, windows)
        # back to block shape for out_specs [K, S, n_loc, ...]
        result = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0], 1) + a.shape[1:]), result)
        return result, steps

    fn = _shard_map(squeeze_fn, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs)
    return jax.jit(fn)


def run(program: VertexProgram, view: GraphView, mesh: Mesh, *,
        window: int | None = None, windows=None,
        sharded_view: ShardedView | None = None, comm: str = "auto",
        block: bool = True):
    """Run a vertex program SPMD over the mesh. Same surface as
    ``engine.bsp.run`` plus the mesh. Returns (result, steps) with result
    leading axes [K windows, n_pad] in GLOBAL vertex order.

    ``comm`` picks the cross-shard state route: ``"all_gather"`` replicates
    the state along the vertex axis each superstep, ``"halo"`` exchanges only
    the remote rows each shard's edges reference (one all_to_all),
    ``"sparse"`` ships only the changed-since-last-superstep rows as
    bucketed compact slices (monotone-min programs only —
    ``parallel/frontier.py``), and ``"auto"`` (default) asks the
    measured-driven chooser (``choose_route``; ``RTPU_COMM_ROUTE``
    forces a route for auto dispatches). docs/COMM.md catalogues the
    routes and the crossover model.

    ``block=False`` returns device arrays without waiting (steps stays a
    device scalar) so a range sweep can overlap the next hop's host fold
    with this hop's supersteps — the mesh twin of ``bsp.run_async``.
    Multi-process runs always block (results must allgather to hosts);
    so does the sparse route (its superstep loop is host-driven)."""
    batched = windows is not None
    occurrences = bool(getattr(program, "needs_occurrences", False))
    if program.combiner == "custom" and program.direction == "both":
        raise ValueError(
            "combiner='custom' requires direction 'out' or 'in' — merging "
            "two custom aggregations is not well-defined")
    if windows is not None and len(windows) == 0:
        raise ValueError("windows must be a non-empty list of window sizes")
    if windows is None:
        windows = [window if window is not None else -1]
    wlist = [int(w) if w is not None and w >= 0 else -1 for w in windows]

    W = mesh.shape.get(W_AXIS, 1)
    S = mesh.shape[V_AXIS]
    # pad window count to a multiple of the window-axis size with no-op
    # duplicates of the last window
    k = len(wlist)
    k_pad = ((k + W - 1) // W) * W
    wlist_p = wlist + [wlist[-1]] * (k_pad - k)
    k_loc = k_pad // W

    sv = sharded_view
    if (sv is None or sv.n_shards != S or sv.view is not view
            or sv.occurrences != occurrences
            or not set(program.edge_props) <= set(sv.d_props)):
        sv = partition_view(view, S, tuple(program.edge_props),
                            occurrences=occurrences)

    if comm not in ("auto",) + COMM_ROUTES:
        raise ValueError(
            f"comm must be auto|halo|all_gather|sparse, got {comm!r}")

    # Multi-host gate: the MESH actually spanning processes, not
    # jax.process_count() — a process of a multi-host cluster sweeping
    # its own local devices must not attempt cross-process collectives.
    multi = len({d.process_index for d in mesh.devices.flat}) > 1

    # Route decision: explicit comm= wins; RTPU_COMM_ROUTE (read HERE,
    # at dispatch — rtpulint RT001) steers "auto"; the measured-driven
    # chooser otherwise picks by estimated bytes/superstep. The decision
    # + evidence is published as a comm.route instant, a journal record,
    # and a /statusz route-table row (docs/COMM.md).
    decision = choose_route(program, view, sv, mesh, comm, k, multi)
    comm = decision["route"]
    proc = TRACER.process_index
    COLLECTIVES.note_route_decision(decision)
    if TRACER.enabled:
        ev = decision["evidence"]
        TRACER.instant(
            "comm.route", process=proc, algorithm=decision["algorithm"],
            route=comm, requested=decision["requested"],
            reason=decision["reason"], density=ev["density"],
            skew_max=ev["skew_max"],
            **{f"est_{r}": b
               for r, b in ev["est_bytes_per_superstep"].items()})
    from ..obs import journal as _journal

    if _journal.enabled():
        _journal.emit("comm.route", decision)

    # mesh-divergence sanitizer: fingerprint this dispatch BEFORE issuing
    # it, so a collective that hangs still leaves its record behind for
    # the /clusterz prefix cross-check. The fingerprint includes the
    # ROUTE — processes disagreeing on the chooser's verdict at the same
    # dispatch seq flag as divergence (tests/test_sparse_route.py)
    msan = mesh_active()
    msite = f"parallel.sharded.run/{type(program).__name__}"
    msig = (f"S{S}W{W}k{k_pad}n{view.n_pad}v{sv.n_loc}"
            f"d{sv.m_loc_d}s{sv.m_loc_s}")
    if msan is not None:
        msan.note_dispatch(msite, comm, msig, "i64")

    if comm == "sparse":
        from . import frontier as _frontier

        with TRACER.span("comm.exchange", route="sparse",
                         direction=program.direction, process=proc,
                         shards=S, windows=k) as csp:
            t0 = _time.perf_counter()
            # rtpulint: spmd-uniform — `comm` is choose_route's verdict, whose every input is replicated by construction (shapes/halo sizes from the partition build, `multi` from the global device list, skew from data-replicated ingestion, density from ALLGATHERED counts; per-process seconds are evidence-only) — all processes pick the same route, and the runtime mesh sanitizer fingerprints the route per dispatch to catch any drift
            result, steps, acct = _frontier.run_sparse(
                program, view, mesh, sv, wlist, multi=multi,
                msan=msan, msite=msite)
            seconds = _time.perf_counter() - t0
            csp.set(supersteps=acct["supersteps"], rows=acct["rows"],
                    bytes=acct["bytes"],
                    density=round(acct["density"], 6),
                    fallback_supersteps=acct["fallback_supersteps"],
                    barrier_wait_seconds=round(acct["barrier_wait"], 6))
        COLLECTIVES.note_exchange(
            "sparse", program.direction, rows=acct["rows"],
            bytes_=acct["bytes"], seconds=seconds,
            supersteps=acct["supersteps"],
            barrier_wait=acct["barrier_wait"])
        COLLECTIVES.note_frontier(decision["key"], acct["density"],
                                  acct["supersteps"])
        from ..obs import ledger as _ledger

        led = _ledger.current()
        if led is not None:
            led.add_dcn("sparse", rows=acct["rows"], bytes_=acct["bytes"])
        if not batched:
            result = jax.tree_util.tree_map(lambda a: a[0], result)
        return result, steps

    # window masks, computed from per-shard latest-time arrays
    v_masks = np.empty((k_pad, S, sv.n_loc), bool)
    d_masks = np.empty((k_pad, S, sv.m_loc_d), bool)
    s_masks = np.empty((k_pad, S, sv.m_loc_s), bool)
    for i, w in enumerate(wlist_p):
        if w < 0:
            v_masks[i] = sv.v_mask
            d_masks[i] = sv.d_mask
            s_masks[i] = sv.s_mask
        else:
            lo = view.time - w
            v_masks[i] = sv.v_mask & (sv.v_latest >= lo)
            d_masks[i] = sv.d_mask & (sv.d_time >= lo)
            s_masks[i] = sv.s_mask & (sv.s_time >= lo)

    # h_* only shape the compiled program on the halo route — keep them out
    # of the runner cache key otherwise, or same-bucket sweep hops with
    # different halo populations would recompile for nothing
    runner = _sharded_runner(
        program, mesh, sv.n_loc, sv.m_loc_d, sv.m_loc_s, k_loc, view.n_pad,
        tuple(program.edge_props), comm,
        sv.h_d if comm == "halo" else 0, sv.h_s if comm == "halo" else 0)

    # Multi-host (DCN) runs: every process holds the same full host arrays
    # (data-replicated ingestion — the reference replays every update to
    # every PM's router the same way), so each input becomes a GLOBAL
    # jax.Array by slicing out this process's addressable shards. On one
    # process this degrades to a plain device put.
    def dev(x, spec):
        if not multi:
            return jnp.asarray(x)
        x = np.asarray(x)
        sh = jax.sharding.NamedSharding(mesh, spec)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    kv, v, rep = P(W_AXIS, V_AXIS), P(V_AXIS), P()
    halo = {}
    if comm == "halo":
        halo = {"d_src_h": dev(sv.d_src_h, v), "d_send": dev(sv.d_send, v),
                "s_dst_h": dev(sv.s_dst_h, v), "s_send": dev(sv.s_send, v)}

    # Collective telemetry: what THIS dispatch moves across shards per
    # superstep. halo ships each device its referenced remote slot pages
    # (padded slot capacity — what is actually on the wire); all_gather
    # replicates the (n_pad - n_loc) remote rows to every device once per
    # superstep, shared by both directions. Byte width is estimated from
    # the result state leaves (the exchanged state tree for every vertex
    # program this engine runs; a program with wider internal state
    # under-counts — documented in docs/OBSERVABILITY.md).
    n_devices = int(mesh.devices.size)
    if comm == "halo":
        rows_dev = sv.halo_rows(program.direction)
    else:
        rows_dev = view.n_pad - sv.n_loc
    rows_step = rows_dev * k_loc * n_devices
    with TRACER.span("comm.exchange", route=comm,
                     direction=program.direction, process=proc,
                     shards=S, windows=k_pad,
                     rows_per_superstep=rows_step) as csp:
        result, steps = runner(
            dev(v_masks, kv), dev(sv.vids, v), dev(sv.v_latest, v),
            dev(sv.v_first, v),
            dev(sv.d_src_g, v), dev(sv.d_dst_l, v), dev(d_masks, kv),
            dev(sv.d_time, v), dev(sv.d_first, v),
            dev(sv.s_dst_g, v), dev(sv.s_src_l, v), dev(s_masks, kv),
            dev(sv.s_time, v), dev(sv.s_first, v),
            halo,
            {kk: dev(vv, v) for kk, vv in sv.d_props.items()},
            {kk: dev(vv, v) for kk, vv in sv.s_props.items()},
            {kk: dev(
                np.asarray(view.vertex_prop(kk),
                           np.float32).reshape(S, sv.n_loc),
                v)
             for kk in program.vertex_props},
            dev(np.asarray(view.time, np.int64), rep),
            dev(np.asarray(wlist_p, np.int64), P(W_AXIS)),
        )
        t_disp = _time.perf_counter()
        row_bytes = sum(
            np.dtype(a.dtype).itemsize
            * int(np.prod(a.shape[3:], dtype=np.int64))
            for a in jax.tree_util.tree_leaves(result))
        block_wait = barrier_wait = 0.0
        if block or multi:
            # local program completion: device compute + in-program
            # collectives — the host-side "collective window"
            with TRACER.span("comm.block_wait", route=comm, process=proc):
                jax.block_until_ready(result)
            block_wait = _time.perf_counter() - t_disp
        # rtpulint: spmd-uniform — `multi` derives from the mesh's device set, which every process builds from the same global device list; all processes take the same arm
        if multi:
            # replicate the (cross-host sharded) result back to every
            # host — job reducers are host code and expect the full
            # arrays. Local compute is DONE here, so this wait is the
            # per-process straggler signal: a process stuck behind a
            # slow peer spends it in this span.
            from jax.experimental import multihost_utils

            t_bar = _time.perf_counter()
            # stall watchdog: divergence shows up as THIS wait never
            # returning (a peer skipped the collective) — the watchdog
            # reports from its timer thread while we are still hung
            watch = (msan.barrier_watch(msite, comm)
                     if msan is not None else None)
            try:
                with TRACER.span("comm.barrier_wait", route=comm,
                                 process=proc):
                    result = multihost_utils.process_allgather(
                        result, tiled=True)
            finally:
                if watch is not None:
                    watch.cancel()
            barrier_wait = _time.perf_counter() - t_bar
            block = True
        # superstep count is a device scalar on async dispatches — those
        # account exactly one superstep (visible as async_dispatches in
        # the COLLECTIVES snapshot) rather than blocking the overlap the
        # async path exists for
        n_steps = int(steps) if block else 1
        rows_total = rows_step * n_steps
        bytes_total = rows_total * row_bytes
        csp.set(supersteps=(n_steps if block else "async"),
                rows=rows_total, bytes=bytes_total,
                barrier_wait_seconds=round(barrier_wait, 6))
    COLLECTIVES.note_exchange(
        comm, program.direction, rows=rows_total, bytes_=bytes_total,
        seconds=block_wait, supersteps=n_steps,
        barrier_wait=barrier_wait, async_dispatch=not block)
    from ..obs import ledger as _ledger

    led = _ledger.current()
    if led is not None:
        led.add_dcn(comm, rows=rows_total, bytes_=bytes_total)
    # merge shard axis back into global vertex order: [K, S, n_loc] -> [K, n]
    to_host = np.asarray if block else (lambda a: a)
    result = jax.tree_util.tree_map(
        lambda a: to_host(a).reshape((k_pad, view.n_pad) + a.shape[3:]),
        result)
    result = jax.tree_util.tree_map(lambda a: a[:k], result)
    if not batched:
        result = jax.tree_util.tree_map(lambda a: a[0], result)
    return result, (int(steps) if block else steps)
