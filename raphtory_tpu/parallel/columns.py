"""Column-sharded range sweeps — view-axis parallelism over a device mesh.

The hop-batched columnar engines (``engine/hopbatch``) evaluate every
(hop, window) view of a range query as an independent COLUMN of one
program. Independence makes the multi-chip mapping trivial and
collective-free: shard the COLUMN axis across all devices of the mesh
(graph tables replicate — they are the small, read-only part), and each
chip runs the same while-loop on its block of views. No halo exchange, no
psum in the superstep loop — the only cross-chip traffic is the initial
replicated-table broadcast. This is the temporal analogue of batch data
parallelism, complementing ``parallel/sharded.py``'s vertex sharding
(which exists for graphs too big for one chip's HBM).

Reference contrast: the reference cannot parallelise ACROSS the hops of a
Range query at all — each hop is a fresh sequential actor handshake
(``RangeAnalysisTask.scala:18-35``); here hops*windows spread over the
whole mesh.
"""

from __future__ import annotations

import time as _time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.hopbatch import (_bfs_columns, _cc_columns, _column_layout,
                               _column_masks, _pagerank_columns, _seed_mask,
                               _tile_budget_bytes)

C_AXIS = "columns"


def run_columns_sharded(tables, e_lat, e_alive, v_lat, v_alive, hop_times,
                        windows, devices, *, kind: str = "pagerank",
                        damping: float = 0.85, tol: float = 1e-7,
                        max_steps: int = 20, seeds=(),
                        directed: bool = False, weight_cols=None):
    """Columnar sweep with the (hop, window) axis sharded over ``devices``
    (any iterable of jax devices, e.g. ``mesh.devices.ravel()``).

    ``kind``: ``"pagerank"`` | ``"cc"`` | ``"bfs"`` (``seeds``/``directed``
    apply; pass ``weight_cols`` ([H, m_pad] f32) for weighted SSSP).
    Returns ``(result [C, n_pad] hop-major, steps)`` — identical values to
    the single-device ``hopbatch`` runners (tested); columns pad up to a
    device multiple internally and the pad is dropped before returning."""
    devices = list(devices)
    n_dev = len(devices)
    H, C, hop_of_col, T_col, w_col = _column_layout(hop_times, windows)
    pad = (-C) % n_dev
    if pad:
        # replicate column 0 into the pad slots — cheapest valid views
        hop_of_col = np.concatenate([hop_of_col,
                                     np.repeat(hop_of_col[:1], pad)])
        T_col = np.concatenate([T_col, np.repeat(T_col[:1], pad)])
        w_col = np.concatenate([w_col, np.repeat(w_col[:1], pad)])

    mesh = Mesh(np.asarray(devices), (C_AXIS,))
    tdt = jnp.dtype(np.dtype(tables.tdtype).name)
    n_pad = tables.n_pad
    extra_host = []
    extra_specs = []
    if kind == "bfs":
        extra_host.append(_seed_mask(tables, seeds))
        extra_specs.append(P())
        if weight_cols is not None:
            extra_host.append(weight_cols)
            extra_specs.append(P())

    # resolved HERE, outside the traced block — an env read at trace time
    # would bake a budget the cache key doesn't carry (rtpulint RT001)
    tile_budget = _tile_budget_bytes()

    def block(e_src, e_dst, el, ea, vl, va, hoc, tc, wc, *extra):
        me, mv = _column_masks(tdt, el, ea, vl, va, hoc, tc, wc)
        if kind == "pagerank":
            out, steps = _pagerank_columns(me, mv, e_src, e_dst, n_pad,
                                           float(damping), float(tol),
                                           int(max_steps),
                                           tile_budget=tile_budget)
        elif kind == "cc":
            out, steps = _cc_columns(me, mv, e_src, e_dst, n_pad,
                                     int(max_steps),
                                     tile_budget=tile_budget)
        elif kind == "bfs":
            ew = extra[1][hoc].T if len(extra) > 1 else 1.0
            out, steps = _bfs_columns(me, mv, e_src, e_dst, n_pad,
                                      int(max_steps), bool(directed),
                                      extra[0], ew,
                                      tile_budget=tile_budget)
        else:
            raise ValueError(f"unknown columnar kind {kind!r}")
        return out, steps[None]   # scalar -> [1] so steps concatenates

    from .sharded import _shard_map

    shard = jax.jit(_shard_map(
        block, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(),   # tables replicate
                  P(C_AXIS), P(C_AXIS), P(C_AXIS), *extra_specs),
        out_specs=(P(C_AXIS), P(C_AXIS))))

    from ..obs.trace import TRACER
    from .sharded import COLLECTIVES

    repl = NamedSharding(mesh, P())
    put = lambda a: jax.device_put(jnp.asarray(a), repl)
    # the only cross-chip traffic on this route is the one-time
    # replicated-table broadcast — account it as the "replicate" route
    # (rows = table rows, bytes = table payload x devices receiving it)
    repl_arrays = [tables.e_src, tables.e_dst, e_lat, e_alive, v_lat,
                   v_alive, *extra_host]
    repl_bytes = int(sum(np.asarray(a).nbytes for a in repl_arrays))
    repl_rows = int(sum(np.asarray(a).shape[-1] if np.asarray(a).ndim
                        else 1 for a in repl_arrays))
    proc = TRACER.process_index
    multi = len({d.process_index for d in mesh.devices.flat}) > 1
    # mesh-divergence sanitizer: fingerprint the dispatch before issuing
    # it (same contract as parallel/sharded.py — a hang still journals)
    from ..analysis.sanitizer import mesh_active

    msan = mesh_active()
    msite = f"parallel.columns.run_columns_sharded/{kind}"
    if msan is not None:
        msan.note_dispatch(msite, "replicate",
                           f"D{n_dev}C{C}n{n_pad}", str(tdt))
    # the column route has exactly one comm shape; record it in the same
    # route table as the vertex-sharded dispatches so /statusz shows the
    # full picture of what moved over the wire and why
    COLLECTIVES.note_route_decision({
        "algorithm": f"columns.{kind}", "route": "replicate",
        "requested": "replicate",
        "reason": "column-sharded dispatch replicates tables once",
        "est_bytes": {"replicate": repl_bytes * max(1, n_dev - 1)},
    })
    t0 = _time.perf_counter()
    with TRACER.span("comm.exchange", route="replicate",
                     direction="columns", process=proc,
                     shards=n_dev, rows=repl_rows * max(1, n_dev - 1),
                     bytes=repl_bytes * max(1, n_dev - 1)):
        result, steps = shard(
            put(tables.e_src), put(tables.e_dst), put(e_lat), put(e_alive),
            put(v_lat), put(v_alive),
            jnp.asarray(hop_of_col), jnp.asarray(T_col), jnp.asarray(w_col),
            *(put(a) for a in extra_host))
        barrier_wait = 0.0
        # rtpulint: spmd-uniform — `multi` derives from the mesh's device set, which every process builds from the same global device list; all processes take the same arm
        if multi:
            # the columns span processes' devices — replicate back to
            # every host (reducers are host code), like
            # parallel/sharded.py does. This wait is the per-process
            # straggler signal on the column-sharded route.
            from jax.experimental import multihost_utils

            jax.block_until_ready(result)
            t_bar = _time.perf_counter()
            watch = (msan.barrier_watch(msite, "replicate")
                     if msan is not None else None)
            try:
                with TRACER.span("comm.barrier_wait", route="replicate",
                                 process=proc):
                    result = multihost_utils.process_allgather(result,
                                                               tiled=True)
                    steps = multihost_utils.process_allgather(steps,
                                                              tiled=True)
            finally:
                if watch is not None:
                    watch.cancel()
            barrier_wait = _time.perf_counter() - t_bar
    COLLECTIVES.note_exchange(
        "replicate", "columns", rows=repl_rows * max(1, n_dev - 1),
        bytes_=repl_bytes * max(1, n_dev - 1),
        seconds=_time.perf_counter() - t0, supersteps=1,
        barrier_wait=barrier_wait)
    return result[:C], int(np.max(np.asarray(steps)))
