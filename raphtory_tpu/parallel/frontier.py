"""Sparse frontier collectives — the third comm route (``comm="sparse"``).

The two existing routes ship state sized by the GRAPH every superstep:
``all_gather`` replicates the whole vertex column, ``halo`` the whole
referenced remote set — both pay the same bill on superstep 30 of a BFS
whose frontier has collapsed to a handful of vertices. This route ships
state sized by the FRONTIER instead, the "Sparse Allreduce" recipe
(PAPERS.md: exchange only the nonzero slices of power-law-distributed
data) fused with "Node Aware SpMV"'s locality rule (aggregate on the
node before crossing the expensive link):

* Each process runs one jitted superstep over its FULL state replica
  ``[k, n_pad]`` using only the edge blocks of the vertex shards it
  owns. Edges are partitioned by destination (and by source for the
  in-direction), so a row's complete aggregate is computed entirely by
  its owner — the per-process kernel IS the node-aware pre-aggregation
  stage: contributions from every locally-owned shard and both edge
  directions min-merge on the host's device before anything reaches DCN
  (``ops/partition`` bucket discipline, applied to the comm plane).
* The changed-since-last-superstep rows are compacted host-side into a
  ``(indices, values)`` slice, padded to a bucketed power-of-two length
  (``ops.partition.frontier_bucket``, floor ``RTPU_SPARSE_BUCKETS``) so
  the ``process_allgather`` shape set stays bounded — no compile storm
  as the frontier grows and collapses (rtpulint RT013 discipline for
  collective shapes).
* One tiny uniform counts-allgather per superstep agrees the global
  bucket length and the halting vote, then the compact slices allgather
  and scatter-merge (elementwise min) into every replica. Monotonicity
  makes the merge exact: ``min(stale, owner_new) == owner_new``, so the
  merged replica is BITWISE the dense route's state (the equivalence
  contract tests/test_sparse_route.py pins across process counts).
* When the measured global frontier density crosses the dense crossover
  (slot bytes ≈ 3x raw row bytes), the bucket ladder tops out at the
  dense slice — the fallback is structural, and the superstep is counted
  in ``fallback_supersteps`` so the route chooser sees it.

Eligibility is the ``VertexProgram.monotone_min`` contract (single min
state leaf, update = masked min, votes == unchanged — see
engine/program.py); everything else stays on the dense routes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.bsp import _merge_aggs
from ..engine.program import Context, Edges, VertexProgram
from ..obs import ledger as _ledger
from ..ops.partition import frontier_bucket, sparse_bucket_floor
from ..ops.segment import segment_combine

#: global frontier density past which a sparse slot (index + value) moves
#: more bytes than the dense row it encodes — supersteps above it count
#: as fallback supersteps in the dispatch accounting (docs/COMM.md
#: "crossover model")
CROSSOVER_DENSITY = 1.0 / 3.0

#: cold-start density prior the route chooser uses before any measured
#: history exists for an (algorithm, window-batch) key — frontier
#: algorithms are sparse by construction, so the first auto dispatch
#: goes sparse and measures itself
PRIOR_DENSITY = 0.05


def supported(program: VertexProgram) -> bool:
    """Sparse-route eligibility: the program declares the monotone
    min-merge contract (engine/program.py ``monotone_min``)."""
    return (bool(getattr(program, "monotone_min", False))
            and program.combiner == "min")


def _min_identity(dtype):
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return np.asarray(np.inf, dt)
    return np.asarray(np.iinfo(dt).max, dt)


def owned_shards(mesh) -> list[int]:
    """Vertex shards this process owns on ``mesh``. Ownership is the
    process of the shard's first device along the other mesh axes — one
    owner per shard even when a window axis spans processes, so exactly
    one process computes (and publishes) each row's update."""
    from .sharded import V_AXIS

    vi = list(mesh.axis_names).index(V_AXIS)
    devs = np.moveaxis(np.asarray(mesh.devices), vi, -1)
    devs = devs[(0,) * (devs.ndim - 1)]
    me = jax.process_index()
    return [s for s in range(devs.shape[0])
            if devs[s].process_index == me]


@functools.lru_cache(maxsize=128)
def _frontier_runner(program: VertexProgram, k: int, n_pad: int,
                     m_d: int, m_s: int, prop_keys: tuple,
                     vprop_keys: tuple):
    """Compiled pieces of the sparse route for (algorithm, shapes): one
    init, one SINGLE-superstep kernel (the multi-process host loop
    drives supersteps — frontier compaction happens between dispatches),
    one whole-sweep while_loop kernel (the single-process fast path),
    one finalize. Frontier SIZES never reach these shapes, so the
    compile-key set per algorithm is exactly these four entries (the
    compile-ring stability tests/test_sparse_route.py pins)."""
    label = type(program).__name__

    def _flat_ids(idx):
        woffs = (jnp.arange(k, dtype=jnp.int32) * n_pad)[:, None]
        return (idx[None, :] + woffs).reshape(-1)

    def _tile(a, m):
        return jnp.broadcast_to(a[None, :], (k,) + a.shape).reshape(
            (k * m,) + a.shape[1:])

    def _degrees(d_dst, d_masks, s_src, s_masks):
        in_deg = segment_combine(
            jnp.ones((k * m_d,), jnp.int32), _flat_ids(d_dst),
            k * n_pad, "sum", d_masks.reshape(-1),
            True).reshape(k, n_pad)
        out_deg = segment_combine(
            jnp.ones((k * m_s,), jnp.int32), _flat_ids(s_src),
            k * n_pad, "sum", s_masks.reshape(-1),
            True).reshape(k, n_pad)
        return in_deg, out_deg

    def _mk_ctx(kk, step, v_masks, vids, v_latest, v_first,
                in_deg, out_deg, vprops, time, windows):
        # the GLOBAL context: full replica, offset 0, no mesh axis — the
        # cross-shard reductions the sharded runner psums are plain sums
        # here because every row is present
        return Context(
            n=n_pad, time=time, window=windows[kk], v_mask=v_masks[kk],
            vids=vids, v_latest_time=v_latest, v_first_time=v_first,
            out_deg=out_deg[kk], in_deg=in_deg[kk],
            n_active=jnp.sum(v_masks[kk].astype(jnp.int32)),
            step=step, vprops=vprops, v_offset=jnp.int32(0),
            axis_name=None)

    def init_fn(v_masks, vids, v_latest, v_first,
                d_dst, d_masks, s_src, s_masks, vprops, time, windows):
        in_deg, out_deg = _degrees(d_dst, d_masks, s_src, s_masks)

        def init_k(kk):
            return program.init(_mk_ctx(
                kk, jnp.int32(0), v_masks, vids, v_latest, v_first,
                in_deg, out_deg, vprops, time, windows))

        return jax.vmap(init_k)(jnp.arange(k))

    def _superstep(state, owned, v_masks, vids, v_latest, v_first,
                   d_src, d_dst, d_masks, d_time, d_first, d_props,
                   s_dst, s_src, s_masks, s_time, s_first, s_props,
                   vprops, time, windows, step, in_deg, out_deg):
        dm, sm = d_masks.reshape(-1), s_masks.reshape(-1)
        state_flat = jax.tree_util.tree_map(
            lambda a: a.reshape((k * n_pad,) + a.shape[2:]), state)

        def gather(ids):
            return jax.tree_util.tree_map(lambda a: a[ids], state_flat)

        agg = None
        if program.direction in ("out", "both"):
            edges = Edges(src=_tile(d_src, m_d), dst=_tile(d_dst, m_d),
                          mask=dm, time=_tile(d_time, m_d),
                          first_time=_tile(d_first, m_d),
                          props={p: _tile(d_props[p], m_d)
                                 for p in prop_keys},
                          step=step)
            payload = program.message(gather(_flat_ids(d_src)), edges)
            agg = jax.tree_util.tree_map(
                lambda x: segment_combine(
                    x, _flat_ids(d_dst), k * n_pad, program.combiner, dm,
                    indices_are_sorted=True,
                ).reshape((k, n_pad) + x.shape[1:]), payload)
        if program.direction in ("in", "both"):
            edges = Edges(src=_tile(s_src, m_s), dst=_tile(s_dst, m_s),
                          mask=sm, time=_tile(s_time, m_s),
                          first_time=_tile(s_first, m_s),
                          props={p: _tile(s_props[p], m_s)
                                 for p in prop_keys},
                          step=step)
            payload = program.message(gather(_flat_ids(s_dst)), edges)
            agg_in = jax.tree_util.tree_map(
                lambda x: segment_combine(
                    x, _flat_ids(s_src), k * n_pad, program.combiner, sm,
                    indices_are_sorted=True,
                ).reshape((k, n_pad) + x.shape[1:]), payload)
            agg = agg_in if agg is None else _merge_aggs(
                program.combiner, agg, agg_in)

        def upd_k(kk, stk, aggk):
            new_st, votes = program.update(stk, aggk, _mk_ctx(
                kk, step, v_masks, vids, v_latest, v_first,
                in_deg, out_deg, vprops, time, windows))
            # non-owned rows belong to their owners' kernels: keep the
            # replica's merged value no matter what update produced (a
            # monotone program leaves them fixed anyway — this makes the
            # ownership boundary structural, not behavioural)
            new_st = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    owned.reshape((n_pad,) + (1,) * (new.ndim - 1)),
                    new, old),
                new_st, stk)
            unhalted = jnp.sum(
                ((~(votes | ~v_masks[kk])) & owned).astype(jnp.int32))
            return new_st, unhalted

        new_state, unhalted_k = jax.vmap(upd_k, in_axes=(0, 0, 0))(
            jnp.arange(k), state, agg)
        changed = jnp.zeros((k, n_pad), bool)
        for new, old in zip(jax.tree_util.tree_leaves(new_state),
                            jax.tree_util.tree_leaves(state)):
            diff = new != old
            if diff.ndim > 2:
                diff = jnp.any(diff, axis=tuple(range(2, diff.ndim)))
            changed = changed | diff
        changed = changed & owned[None, :]
        return new_state, changed, jnp.sum(unhalted_k)

    def step_fn(state, owned, v_masks, vids, v_latest, v_first,
                d_src, d_dst, d_masks, d_time, d_first, d_props,
                s_dst, s_src, s_masks, s_time, s_first, s_props,
                vprops, time, windows, step):
        in_deg, out_deg = _degrees(d_dst, d_masks, s_src, s_masks)
        return _superstep(
            state, owned, v_masks, vids, v_latest, v_first,
            d_src, d_dst, d_masks, d_time, d_first, d_props,
            s_dst, s_src, s_masks, s_time, s_first, s_props,
            vprops, time, windows, step, in_deg, out_deg)

    def sweep_fn(state, owned, v_masks, vids, v_latest, v_first,
                 d_src, d_dst, d_masks, d_time, d_first, d_props,
                 s_dst, s_src, s_masks, s_time, s_first, s_props,
                 vprops, time, windows):
        # the SINGLE-process whole-sweep kernel: with one participating
        # process there is no exchange between supersteps, so the host
        # loop (one dispatch + device sync per superstep) collapses into
        # the dense route's while_loop — dispatch parity with all_gather
        # — while the per-superstep changed counts still come back for
        # the frontier accounting. Same _superstep body as the multi
        # path, so results stay bitwise identical.
        in_deg, out_deg = _degrees(d_dst, d_masks, s_src, s_masks)

        def body(carry):
            st, step, _, counts = carry
            new_state, changed, unhalted = _superstep(
                st, owned, v_masks, vids, v_latest, v_first,
                d_src, d_dst, d_masks, d_time, d_first, d_props,
                s_dst, s_src, s_masks, s_time, s_first, s_props,
                vprops, time, windows, step, in_deg, out_deg)
            counts = counts.at[step].set(
                jnp.sum(changed, dtype=jnp.int32))
            return (new_state, step + jnp.int32(1),
                    unhalted.astype(jnp.int32), counts)

        def cond(carry):
            _, step, unh, _ = carry
            return (step < program.max_steps) & (unh > 0)

        carry = (state, jnp.int32(0), jnp.int32(1),
                 jnp.zeros((max(1, program.max_steps),), jnp.int32))
        st, steps, _, counts = jax.lax.while_loop(cond, body, carry)
        return st, steps, counts

    def finalize_fn(state, v_masks, vids, v_latest, v_first,
                    d_dst, d_masks, s_src, s_masks, vprops, time,
                    windows, steps):
        in_deg, out_deg = _degrees(d_dst, d_masks, s_src, s_masks)

        def fin_k(kk, st):
            return program.finalize(st, _mk_ctx(
                kk, steps, v_masks, vids, v_latest, v_first,
                in_deg, out_deg, vprops, time, windows))

        return jax.vmap(fin_k, in_axes=(0, 0))(jnp.arange(k), state)

    return {
        "init": _ledger.instrument(f"frontier.init.{label}",
                                   jax.jit(init_fn)),
        "step": _ledger.instrument(f"frontier.superstep.{label}",
                                   jax.jit(step_fn)),
        "sweep": _ledger.instrument(f"frontier.sweep.{label}",
                                    jax.jit(sweep_fn)),
        "finalize": _ledger.instrument(f"frontier.finalize.{label}",
                                       jax.jit(finalize_fn)),
    }


def _flat_blocks(sv, owned, wlist, time):
    """Concatenate the owned shards' edge blocks into flat GLOBAL-index
    arrays + per-window masks. Shard-local sorted dst/src plus ascending
    shard offsets keep the flat segment ids sorted — the
    ``indices_are_sorted`` contract of the combine."""
    n_loc = sv.n_loc
    offs = (np.asarray(owned, np.int64) * n_loc).astype(np.int32)
    sel = list(owned)

    def flat(a):
        return a[sel].reshape(-1)

    d_src = flat(sv.d_src_g)
    d_dst = (sv.d_dst_l[sel] + offs[:, None]).reshape(-1)
    d_mask = flat(sv.d_mask)
    d_time = flat(sv.d_time)
    d_first = flat(sv.d_first)
    s_dst = flat(sv.s_dst_g)
    s_src = (sv.s_src_l[sel] + offs[:, None]).reshape(-1)
    s_mask = flat(sv.s_mask)
    s_time = flat(sv.s_time)
    s_first = flat(sv.s_first)
    d_props = {p: flat(a) for p, a in sv.d_props.items()}
    s_props = {p: flat(a) for p, a in sv.s_props.items()}

    k = len(wlist)
    d_masks = np.empty((k, d_mask.size), bool)
    s_masks = np.empty((k, s_mask.size), bool)
    for i, w in enumerate(wlist):
        if w < 0:
            d_masks[i] = d_mask
            s_masks[i] = s_mask
        else:
            lo = time - w
            d_masks[i] = d_mask & (d_time >= lo)
            s_masks[i] = s_mask & (s_time >= lo)
    return {
        "d_src": d_src, "d_dst": d_dst, "d_masks": d_masks,
        "d_time": d_time, "d_first": d_first, "d_props": d_props,
        "s_dst": s_dst, "s_src": s_src, "s_masks": s_masks,
        "s_time": s_time, "s_first": s_first, "s_props": s_props,
    }


def run_sparse(program: VertexProgram, view, mesh, sv, wlist,
               *, multi: bool, msan=None, msite: str = ""):
    """Host-driven sparse-frontier superstep loop. Returns
    ``(result_tree [k, n_pad, ...], steps, acct)`` with ``acct`` the
    exchange accounting the dispatcher folds into ``CollectiveStats``
    and the ledger ``dcn`` block.

    Every cross-process collective here is SPMD-uniform by construction:
    bucket lengths and halting derive from the allgathered per-process
    counts, never from process-local state (the RT012 pragma-free design
    docs/COMM.md documents)."""
    if not supported(program):
        raise ValueError(
            f"{type(program).__name__} is not sparse-route eligible: "
            "comm='sparse' needs the monotone_min contract "
            "(engine/program.py)")
    k = len(wlist)
    n_pad = int(view.n_pad)
    owned = owned_shards(mesh)
    owned_mask = np.zeros(n_pad, bool)
    for s in owned:
        owned_mask[s * sv.n_loc: (s + 1) * sv.n_loc] = True
    blocks = _flat_blocks(sv, owned, wlist, int(view.time))
    m_d = int(blocks["d_src"].size)
    m_s = int(blocks["s_dst"].size)
    fns = _frontier_runner(
        program, k, n_pad, m_d, m_s, tuple(program.edge_props),
        tuple(program.vertex_props))

    v_mask = np.asarray(view.v_mask).reshape(-1)
    v_latest = np.asarray(view.v_latest_time).reshape(-1)
    v_first = np.asarray(view.v_first_time).reshape(-1)
    v_masks = np.empty((k, n_pad), bool)
    for i, w in enumerate(wlist):
        v_masks[i] = v_mask if w < 0 else v_mask & (v_latest >= (view.time - w))
    vids = np.asarray(view.vids).reshape(-1)
    vprops = {p: np.asarray(view.vertex_prop(p), np.float32).reshape(-1)
              for p in program.vertex_props}
    time = np.asarray(view.time, np.int64)
    windows = np.asarray(wlist, np.int64)

    # device-put every loop-invariant operand ONCE: the superstep kernel
    # redispatches per superstep (the host drives the loop), and passing
    # host arrays would re-transfer the multi-MB edge blocks every step
    put = jax.device_put
    v_masks = put(v_masks)
    vids, v_latest, v_first = put(vids), put(v_latest), put(v_first)
    vprops = {p: put(a) for p, a in vprops.items()}
    blocks = {kk: ({p: put(a) for p, a in vv.items()}
                   if isinstance(vv, dict) else put(vv))
              for kk, vv in blocks.items()}
    owned_dev = put(owned_mask)

    ctx_args = (v_masks, vids, v_latest, v_first,
                blocks["d_dst"], blocks["d_masks"],
                blocks["s_src"], blocks["s_masks"],
                vprops, time, windows)
    state = fns["init"](*ctx_args)
    leaves = jax.tree_util.tree_leaves(state)
    if len(leaves) != 1:
        raise ValueError(
            f"{type(program).__name__}.monotone_min promises a single "
            f"state leaf; init() returned {len(leaves)}")
    state_np = np.asarray(leaves[0])
    treedef = jax.tree_util.tree_structure(state)
    identity = _min_identity(state_np.dtype)
    trailing = state_np.shape[2:]
    trail_items = int(np.prod(trailing, dtype=np.int64)) if trailing else 1
    slot_bytes = 8 + state_np.dtype.itemsize * trail_items
    floor = sparse_bucket_floor()
    n_procs = len({d.process_index for d in mesh.devices.flat})

    steps = 0
    halted = False
    rows_total = 0
    bytes_total = 0
    fallback_steps = 0
    density_sum = 0.0
    barrier_wait = 0.0
    if multi:
        from jax.experimental import multihost_utils
    import time as _time

    state_dev = state     # supersteps stay device-resident between rounds
    if not multi:
        # with one participating process there is no exchange between
        # supersteps, so the whole sweep collapses into a single
        # while_loop dispatch — dispatch parity with the dense route —
        # while the per-superstep changed counts come back for the
        # frontier accounting below
        state_dev, steps_dev, step_counts = fns["sweep"](
            state_dev,
            owned_dev, v_masks, vids, v_latest, v_first,
            blocks["d_src"], blocks["d_dst"], blocks["d_masks"],
            blocks["d_time"], blocks["d_first"], blocks["d_props"],
            blocks["s_dst"], blocks["s_src"], blocks["s_masks"],
            blocks["s_time"], blocks["s_first"], blocks["s_props"],
            vprops, time, windows)
        steps = int(steps_dev)
        for cnt in np.asarray(step_counts)[:steps]:
            cnt = int(cnt)
            # single-process dispatches publish their slice slots too —
            # the bytes THIS superstep would put on DCN, so the route's
            # accounting (and the cluster smoke's nonzero-sparse-bytes
            # assertion) is mesh-size independent
            B = frontier_bucket(cnt, floor, cap=k * n_pad)
            rows_total += B
            bytes_total += B * slot_bytes
            density = cnt / float(k * n_pad)
            density_sum += density
            if density > CROSSOVER_DENSITY:
                fallback_steps += 1
    while multi and steps < program.max_steps and not halted:
        new, changed, unhalted = fns["step"](
            state_dev,
            owned_dev, v_masks, vids, v_latest, v_first,
            blocks["d_src"], blocks["d_dst"], blocks["d_masks"],
            blocks["d_time"], blocks["d_first"], blocks["d_props"],
            blocks["s_dst"], blocks["s_src"], blocks["s_masks"],
            blocks["s_time"], blocks["s_first"], blocks["s_props"],
            vprops, time, windows, np.int32(steps))
        ch = np.asarray(changed).reshape(-1)
        loc_idx = np.flatnonzero(ch)
        cnt = int(loc_idx.size)
        unh = int(unhalted)
        new_np = np.asarray(jax.tree_util.tree_leaves(new)[0])
        flat_new = new_np.reshape((k * n_pad,) + trailing)
        # counts first: ONE uniform agreement round fixes the bucket
        # length and the halting vote for every process — the bucket
        # (hence the slice collective's shape) is a pure function of
        # allgathered data, never of local state
        t_bar = _time.perf_counter()
        watch = (msan.barrier_watch(msite, "sparse")
                 if msan is not None else None)
        try:
            counts = multihost_utils.process_allgather(
                np.asarray([cnt, unh], np.int64))
        finally:
            if watch is not None:
                watch.cancel()
        counts = np.asarray(counts).reshape(-1, 2)
        cmax = int(counts[:, 0].max())
        cglobal = int(counts[:, 0].sum())
        unh_g = int(counts[:, 1].sum())
        B = frontier_bucket(cmax, floor, cap=k * n_pad)
        idx = np.zeros(B, np.int64)
        idx[:cnt] = loc_idx
        val = np.full((B,) + trailing, identity, state_np.dtype)
        val[:cnt] = flat_new[loc_idx]
        watch = (msan.barrier_watch(msite, "sparse")
                 if msan is not None else None)
        try:
            slices = multihost_utils.process_allgather(
                {"idx": idx, "val": val})
        finally:
            if watch is not None:
                watch.cancel()
        barrier_wait += _time.perf_counter() - t_bar
        # scatter-merge every process's slice into the replica —
        # elementwise min, so identity pads and own rows are no-ops
        # and merge order cannot matter
        base = state_np.reshape((k * n_pad,) + trailing).copy()
        np.minimum.at(base,
                      np.asarray(slices["idx"]).reshape(-1),
                      np.asarray(slices["val"]).reshape(
                          (-1,) + trailing))
        state_np = base.reshape((k, n_pad) + trailing)
        state_dev = jax.tree_util.tree_unflatten(
            treedef, [put(state_np)])
        rows_step = B * n_procs
        bytes_step = rows_step * slot_bytes + 16 * n_procs
        density = cglobal / float(k * n_pad)
        density_sum += density
        if density > CROSSOVER_DENSITY:
            fallback_steps += 1
        rows_total += rows_step
        bytes_total += bytes_step
        steps += 1
        halted = unh_g == 0

    result = fns["finalize"](
        state_dev,
        v_masks, vids, v_latest, v_first,
        blocks["d_dst"], blocks["d_masks"],
        blocks["s_src"], blocks["s_masks"],
        vprops, time, windows, np.int32(steps))
    result = jax.tree_util.tree_map(np.asarray, result)
    acct = {
        "rows": rows_total,
        "bytes": bytes_total,
        "supersteps": steps,
        "barrier_wait": barrier_wait,
        "density": (density_sum / steps) if steps else 0.0,
        "fallback_supersteps": fallback_steps,
        "processes": n_procs,
        "owned_shards": len(owned),
    }
    return result, steps, acct
