"""ctypes bindings for the native kernel library.

Every function here mirrors a numpy implementation elsewhere in the package;
callers use ``native.fold_latest or numpy_path`` style dispatch. The library
compiles lazily on first use (``native/build.py``) and failure to build just
means the numpy paths run.
"""

from __future__ import annotations

import ctypes
import logging
import threading

import numpy as np

_log = logging.getLogger(__name__)
_lib = None
_tried = False
_load_lock = threading.Lock()   # first use may g++-build the library —
# concurrent first callers (e.g. the sweep's overlapped vertex fold) must
# not race the build/latch

_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    with _load_lock:
        if _tried:
            return _lib
        return _load_locked()


def _load_locked():
    global _lib, _tried
    try:
        _lib = _build_and_bind()
    finally:
        # set LAST (under the lock, after _lib publishes) so the unlocked
        # fast path never observes _tried before _lib
        _tried = True
    return _lib


def _build_and_bind():
    from .build import lib_path

    path = lib_path()
    if path is None:
        # one-time heads-up: every `_native.x or numpy` dispatch in the
        # package now takes the interpreted path (including the O(queries)
        # _lex_lookup loop on edge-property materialisation)
        _log.warning(
            "raphtory_tpu native kernels unavailable (build disabled or "
            "failed) — falling back to slower numpy/Python paths")
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:
        _log.warning(
            "raphtory_tpu native kernel library failed to load (%s) — "
            "falling back to slower numpy/Python paths", e)
        return None
    lib.rtpu_sort_events.restype = None
    lib.rtpu_sort_events.argtypes = [
        ctypes.c_int64, _i64p, _i64p, _i64p, _u8p, _i64p]
    lib.rtpu_fold_sorted.restype = ctypes.c_int64
    lib.rtpu_fold_sorted.argtypes = [
        ctypes.c_int64, _i64p, _i64p, _i64p, _u8p, _i64p,
        _i64p, _i64p, _i64p, _u8p, _i64p]
    lib.rtpu_lex_lookup2.restype = None
    lib.rtpu_lex_lookup2.argtypes = [
        ctypes.c_int64, _i64p, _i64p, ctypes.c_int64, _i64p, _i64p, _i64p]
    lib.rtpu_parse_int_csv.restype = ctypes.c_int64
    lib.rtpu_parse_int_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, _i64p,
        ctypes.c_int64, _i64p, ctypes.c_int64]
    _u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.rtpu_radix_argsort_u64.restype = None
    lib.rtpu_radix_argsort_u64.argtypes = [ctypes.c_int64, _u64p, _i64p]
    lib.rtpu_searchsorted_u64.restype = None
    lib.rtpu_searchsorted_u64.argtypes = [
        ctypes.c_int64, _u64p, ctypes.c_int64, _u64p, ctypes.c_int32, _i64p]
    return lib


def available() -> bool:
    return _load() is not None


def _p64(a: np.ndarray):
    return a.ctypes.data_as(_i64p)


def _pu8(a: np.ndarray):
    return a.ctypes.data_as(_u8p)


def _c64(a) -> np.ndarray:
    return np.ascontiguousarray(a, np.int64)


def sort_events(keys: tuple, times, alive) -> np.ndarray | None:
    """Argsort by (keys..., time, alive-first); np.lexsort((~alive, times,
    *reversed(keys))) equivalent. None when the native lib is unavailable."""
    lib = _load()
    if lib is None or len(keys) not in (1, 2):
        return None
    n = len(times)
    k1 = _c64(keys[0])
    k2 = _c64(keys[1]) if len(keys) == 2 else None
    t = _c64(times)
    a = np.ascontiguousarray(alive, np.uint8)
    order = np.empty(n, np.int64)
    lib.rtpu_sort_events(
        n, _p64(k1), _p64(k2) if k2 is not None else None,
        _p64(t), _pu8(a), _p64(order))
    return order


def fold_latest(keys: tuple, times, alive):
    """Native _fold_latest: (unique_keys, latest_time, latest_alive,
    first_time). None when unavailable."""
    lib = _load()
    if lib is None or len(keys) not in (1, 2):
        return None
    n = len(times)
    if n == 0:
        empty = tuple(np.empty(0, np.int64) for _ in keys)
        return empty, np.empty(0, np.int64), np.empty(0, bool), np.empty(0, np.int64)
    k1 = _c64(keys[0])
    k2 = _c64(keys[1]) if len(keys) == 2 else None
    t = _c64(times)
    a = np.ascontiguousarray(alive, np.uint8)
    order = np.empty(n, np.int64)
    lib.rtpu_sort_events(
        n, _p64(k1), _p64(k2) if k2 is not None else None,
        _p64(t), _pu8(a), _p64(order))
    ok1 = np.empty(n, np.int64)
    ok2 = np.empty(n, np.int64) if k2 is not None else None
    olat = np.empty(n, np.int64)
    oal = np.empty(n, np.uint8)
    ofst = np.empty(n, np.int64)
    g = lib.rtpu_fold_sorted(
        n, _p64(k1), _p64(k2) if k2 is not None else None,
        _p64(t), _pu8(a), _p64(order),
        _p64(ok1), _p64(ok2) if ok2 is not None else None,
        _p64(olat), _pu8(oal), _p64(ofst))
    out_keys = (ok1[:g].copy(),)
    if ok2 is not None:
        out_keys = (ok1[:g].copy(), ok2[:g].copy())
    return out_keys, olat[:g].copy(), oal[:g].astype(bool), ofst[:g].copy()


def lex_lookup2(b1, b2, q1, q2) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    b1 = _c64(b1)
    b2 = _c64(b2)
    q1 = _c64(q1)
    q2 = _c64(q2)
    out = np.empty(len(q1), np.int64)
    lib.rtpu_lex_lookup2(
        len(b1), _p64(b1), _p64(b2), len(q1), _p64(q1), _p64(q2), _p64(out))
    return out


def radix_argsort_u64(keys: np.ndarray) -> np.ndarray:
    """STABLE argsort of uint64 keys — parallel native radix when available
    (seconds at 100M keys), numpy stable sort otherwise."""
    lib = _load()
    keys = np.ascontiguousarray(keys, np.uint64)
    if lib is None:
        return np.argsort(keys, kind="stable")
    order = np.empty(len(keys), np.int64)
    lib.rtpu_radix_argsort_u64(
        len(keys), keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _p64(order))
    return order


def searchsorted_u64(base: np.ndarray, queries: np.ndarray,
                     side: str = "left") -> np.ndarray:
    """Parallel batched searchsorted over a sorted uint64 array."""
    lib = _load()
    base = np.ascontiguousarray(base, np.uint64)
    queries = np.ascontiguousarray(queries, np.uint64)
    if lib is None:
        return np.searchsorted(base, queries, side=side)
    out = np.empty(len(queries), np.int64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.rtpu_searchsorted_u64(
        len(base), base.ctypes.data_as(u64p),
        len(queries), queries.ctypes.data_as(u64p),
        1 if side == "right" else 0, _p64(out))
    return out


def parse_int_csv(data: bytes, sep: str, cols: tuple) -> np.ndarray | None:
    """Extract int64 columns (ascending 0-based indices) from a CSV byte
    buffer; returns array[len(cols), rows] or None when unavailable."""
    lib = _load()
    if lib is None or len(cols) > 16:
        return None
    sep_b = sep.encode()
    if len(sep_b) != 1:  # multi-byte separator: only the row path handles it
        return None
    max_rows = data.count(b"\n") + 1
    cols_a = _c64(np.asarray(cols, np.int64))
    out = np.empty((len(cols), max_rows), np.int64)
    rows = lib.rtpu_parse_int_csv(
        data, len(data), ctypes.c_char(sep_b), _p64(cols_a),
        len(cols), _p64(out), max_rows)
    return np.ascontiguousarray(out[:, :rows])
