"""Compile the native kernel library on first import, cached by source hash.

No pybind11 in this image; the library is plain C ABI consumed via ctypes.
Set ``RTPU_NATIVE=0`` to disable native kernels entirely (pure numpy paths).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sysconfig
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "kernels.cpp"
_BUILD = _HERE / "_build"


def _lib_suffix() -> str:
    return sysconfig.get_config_var("SHLIB_SUFFIX") or ".so"


def lib_path() -> Path | None:
    """Path of the compiled library, building it if needed. None on failure
    or when RTPU_NATIVE=0."""
    if os.environ.get("RTPU_NATIVE", "1") == "0":
        return None
    try:
        src = _SRC.read_bytes()
    except OSError:
        return None
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _BUILD / f"librtpu_{tag}{_lib_suffix()}"
    if out.exists():
        return out
    _BUILD.mkdir(exist_ok=True)
    # compile to a per-process temp name, then publish atomically — a killed
    # or concurrent build can never leave a half-written library at `out`
    tmp = out.with_name(f".{out.name}.{os.getpid()}.tmp")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-fno-math-errno", "-o", str(tmp), str(_SRC),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except (subprocess.SubprocessError, OSError):
        tmp.unlink(missing_ok=True)
        return None
    return out if out.exists() else None
