// raphtory_tpu native runtime kernels.
//
// The reference's performance-critical host layer is the JVM/Akka actor
// runtime (SURVEY §2.9); here the host hot loops around the TPU compute path
// are native C++: the snapshot-builder's event sorts (the graph-builder), the
// sorted two-column join used by property materialisation, and the ingest
// CSV tokeniser (the data-loader). Loaded from Python via ctypes
// (`raphtory_tpu/native/lib.py`); every entry point has a pure-numpy
// fallback, so this library is an accelerator, not a dependency.
//
// Build: g++ -O3 -shared -fPIC (see native/build.py). Plain C ABI.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Argsort event rows by (k1[, k2], time, alive-first) — the order
// np.lexsort((~alive, times, k2, k1)) produces. At equal (key, time) dead
// rows sort last so a "last row of group" scan picks the tombstone
// (delete-wins tie-break of the temporal fold; Entity.scala:41-57 semantics).
// k2 may be null for single-key streams. order_out: int64[n].
void rtpu_sort_events(int64_t n, const int64_t* k1, const int64_t* k2,
                      const int64_t* times, const uint8_t* alive,
                      int64_t* order_out) {
    for (int64_t i = 0; i < n; ++i) order_out[i] = i;
    if (k2 != nullptr) {
        std::sort(order_out, order_out + n, [&](int64_t a, int64_t b) {
            if (k1[a] != k1[b]) return k1[a] < k1[b];
            if (k2[a] != k2[b]) return k2[a] < k2[b];
            if (times[a] != times[b]) return times[a] < times[b];
            return alive[a] > alive[b];
        });
    } else {
        std::sort(order_out, order_out + n, [&](int64_t a, int64_t b) {
            if (k1[a] != k1[b]) return k1[a] < k1[b];
            if (times[a] != times[b]) return times[a] < times[b];
            return alive[a] > alive[b];
        });
    }
}

// Fused group fold over rows already sorted by rtpu_sort_events: one output
// row per distinct key with (latest_time, latest_alive, first_time) — the
// whole _fold_latest in one pass. Returns the group count.
int64_t rtpu_fold_sorted(int64_t n, const int64_t* k1, const int64_t* k2,
                         const int64_t* times, const uint8_t* alive,
                         const int64_t* order,
                         int64_t* out_k1, int64_t* out_k2,
                         int64_t* out_latest_t, uint8_t* out_alive,
                         int64_t* out_first_t) {
    int64_t g = -1;
    for (int64_t i = 0; i < n; ++i) {
        int64_t r = order[i];
        bool fresh = (g < 0) || k1[r] != out_k1[g] ||
                     (k2 != nullptr && k2[r] != out_k2[g]);
        if (fresh) {
            ++g;
            out_k1[g] = k1[r];
            if (k2 != nullptr) out_k2[g] = k2[r];
            out_first_t[g] = times[r];
        }
        out_latest_t[g] = times[r];
        out_alive[g] = alive[r];
    }
    return g + 1;
}

// Position of each (q1, q2) pair in key columns sorted lexicographically by
// (b1, b2); -1 when absent. Replaces the per-query Python loop in
// snapshot._lex_lookup (edge-property materialisation hot path).
void rtpu_lex_lookup2(int64_t nb, const int64_t* b1, const int64_t* b2,
                      int64_t nq, const int64_t* q1, const int64_t* q2,
                      int64_t* out) {
    for (int64_t i = 0; i < nq; ++i) {
        const int64_t* lo = std::lower_bound(b1, b1 + nb, q1[i]);
        const int64_t* hi = std::upper_bound(lo, b1 + nb, q1[i]);
        if (lo == hi) { out[i] = -1; continue; }
        int64_t l = lo - b1, h = hi - b1;
        const int64_t* p = std::lower_bound(b2 + l, b2 + h, q2[i]);
        out[i] = (p != b2 + h && *p == q2[i]) ? (p - b2) : -1;
    }
}

// CSV integer-column tokeniser: extract up to `ncols` int64 columns (by
// 0-based column index, ascending) from a newline-separated byte buffer.
// Rows with missing/non-numeric cells are skipped. Returns rows written.
// outs: ncols pointers worth of int64[max_rows] laid out contiguously as
// out[c * max_rows + row].
int64_t rtpu_parse_int_csv(const char* buf, int64_t len, char sep,
                           const int64_t* cols, int64_t ncols,
                           int64_t* out, int64_t max_rows) {
    int64_t row = 0;
    const char* p = buf;
    const char* end = buf + len;
    int64_t vals[16];
    while (p < end && row < max_rows) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', end - p));
        if (!line_end) line_end = end;
        int64_t col = 0, want = 0;
        bool ok = true;
        const char* q = p;
        while (want < ncols && q <= line_end) {
            const char* cell_end = q;
            while (cell_end < line_end && *cell_end != sep) ++cell_end;
            if (col == cols[want]) {
                // Parse int64 exactly like Python's int(cell): optional
                // sign, digits only, surrounding whitespace tolerated
                // (includes the \r of CRLF files). Anything else — floats,
                // empty cells — rejects the row, matching the row path.
                const char* c = q;
                const char* ce = cell_end;
                while (c < ce && (*c == ' ' || *c == '\t')) ++c;
                while (ce > c && (ce[-1] == ' ' || ce[-1] == '\t' ||
                                  ce[-1] == '\r')) --ce;
                bool neg = false;
                if (c < ce && (*c == '-' || *c == '+')) {
                    neg = (*c == '-');
                    ++c;
                }
                if (c == ce || *c < '0' || *c > '9') { ok = false; break; }
                int64_t v = 0;
                // digits with Python-style single '_' grouping: an
                // underscore is legal only BETWEEN two digits (int("1_0")
                // == 10; "_1", "1_", "1__0" all reject) — keeps the bulk
                // path row-for-row identical to the int() row path.
                while (c < ce) {
                    if (*c >= '0' && *c <= '9') {
                        v = v * 10 + (*c++ - '0');
                    } else if (*c == '_' && c + 1 < ce &&
                               c[1] >= '0' && c[1] <= '9') {
                        ++c;
                    } else {
                        break;
                    }
                }
                if (c != ce) { ok = false; break; }
                vals[want++] = neg ? -v : v;
            }
            ++col;
            if (cell_end == line_end) break;
            q = cell_end + 1;
        }
        if (ok && want == ncols) {
            for (int64_t c2 = 0; c2 < ncols; ++c2)
                out[c2 * max_rows + row] = vals[c2];
            ++row;
        }
        p = line_end + 1;
    }
    return row;
}


// ---------------------------------------------------------------- bulk load

// Parallel stable LSD radix argsort of uint64 keys. The bulk-load hot sort:
// 100M keys in seconds where std::sort takes minutes. Stability preserves
// the caller's time order within equal keys (the (pair, time) trick the
// bulk loader relies on). order_out: int64[n].

void rtpu_radix_argsort_u64(int64_t n, const uint64_t* keys,
                            int64_t* order_out) {
    const int PASSES = 8, BUCKETS = 256;
    int nt = (int)std::thread::hardware_concurrency();
    if (nt < 1) nt = 1;
    if (nt > 32) nt = 32;
    if (n < (1 << 16)) nt = 1;

    std::vector<uint64_t> kbuf(n);
    std::vector<int64_t> obuf(n);
    std::vector<uint64_t> kbuf2(n);
    std::vector<int64_t> obuf2(n);
    for (int64_t i = 0; i < n; ++i) { kbuf[i] = keys[i]; obuf[i] = i; }

    uint64_t* ks = kbuf.data(); int64_t* os = obuf.data();
    uint64_t* kd = kbuf2.data(); int64_t* od = obuf2.data();

    std::vector<int64_t> hist((size_t)nt * BUCKETS);
    int64_t chunk = (n + nt - 1) / nt;

    for (int pass = 0; pass < PASSES; ++pass) {
        int shift = pass * 8;
        // skip passes whose byte is constant (common: high bytes of ids)
        std::fill(hist.begin(), hist.end(), 0);
        auto count = [&](int t) {
            int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
            int64_t* h = &hist[(size_t)t * BUCKETS];
            for (int64_t i = lo; i < hi; ++i)
                ++h[(ks[i] >> shift) & 0xff];
        };
        {
            std::vector<std::thread> th;
            for (int t = 1; t < nt; ++t) th.emplace_back(count, t);
            count(0);
            for (auto& x : th) x.join();
        }
        int nonzero = 0; int64_t first_total = 0;
        for (int b = 0; b < BUCKETS && nonzero <= 1; ++b) {
            int64_t tot = 0;
            for (int t = 0; t < nt; ++t) tot += hist[(size_t)t * BUCKETS + b];
            if (tot) { ++nonzero; first_total = tot; }
        }
        if (nonzero <= 1 && first_total == n) continue;  // constant byte
        // exclusive prefix, bucket-major then thread order (stability)
        int64_t run = 0;
        for (int b = 0; b < BUCKETS; ++b) {
            for (int t = 0; t < nt; ++t) {
                int64_t c = hist[(size_t)t * BUCKETS + b];
                hist[(size_t)t * BUCKETS + b] = run;
                run += c;
            }
        }
        auto scatter = [&](int t) {
            int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
            int64_t* h = &hist[(size_t)t * BUCKETS];
            for (int64_t i = lo; i < hi; ++i) {
                int64_t p = h[(ks[i] >> shift) & 0xff]++;
                kd[p] = ks[i]; od[p] = os[i];
            }
        };
        {
            std::vector<std::thread> th;
            for (int t = 1; t < nt; ++t) th.emplace_back(scatter, t);
            scatter(0);
            for (auto& x : th) x.join();
        }
        std::swap(ks, kd); std::swap(os, od);
    }
    std::memcpy(order_out, os, (size_t)n * sizeof(int64_t));
}

// Parallel batched lower/upper bound over a sorted u64 array — the per-hop
// latest-event lookup of the bulk loader (100M queries/hop).
// side: 0 = left (lower_bound), 1 = right (upper_bound). out: int64[nq].
void rtpu_searchsorted_u64(int64_t nb, const uint64_t* base,
                           int64_t nq, const uint64_t* queries,
                           int32_t side, int64_t* out) {
    int nt = (int)std::thread::hardware_concurrency();
    if (nt < 1) nt = 1;
    if (nt > 32) nt = 32;
    if (nq < (1 << 14)) nt = 1;
    int64_t chunk = (nq + nt - 1) / nt;
    auto work = [&](int t) {
        int64_t lo = t * chunk, hi = std::min(nq, lo + chunk);
        for (int64_t i = lo; i < hi; ++i) {
            const uint64_t* p = side
                ? std::upper_bound(base, base + nb, queries[i])
                : std::lower_bound(base, base + nb, queries[i]);
            out[i] = (int64_t)(p - base);
        }
    };
    std::vector<std::thread> th;
    for (int t = 1; t < nt; ++t) th.emplace_back(work, t);
    work(0);
    for (auto& x : th) x.join();
}

}  // extern "C"

