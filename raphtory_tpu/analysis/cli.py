"""rtpulint command line — scan, baseline-diff, report.

``tools/rtpulint raphtory_tpu/`` is the CI entry point: exit 0 when every
finding is covered by the checked-in baseline, exit 1 on new findings (or
parse errors), exit 2 on usage errors. ``--write-baseline`` refreshes the
baseline after a reviewed change; ``--format json`` emits the machine
report CI uploads as an artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .findings import Baseline
from .rules import RULES, analyze_project

DEFAULT_BASELINE = os.path.join("tools", "rtpulint_baseline.json")
DEFAULT_DOCS = os.path.join("docs", "OPERATIONS.md")


def _iter_py_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                       if f.endswith(".py"))
    return out


def _load(path: str, root: str) -> tuple[str, str]:
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8") as fh:
        return rel.replace(os.sep, "/"), fh.read()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtpulint",
        description="project-specific static analysis for raphtory_tpu "
                    "(rule catalogue: docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="+", help="files or directories to scan")
    ap.add_argument("--root", default=".",
                    help="repo root findings are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline json (default: <root>/{DEFAULT_BASELINE} "
                         f"when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings as the new baseline")
    ap.add_argument("--docs", default=None,
                    help=f"knob-table doc for undocumented-knob "
                         f"(default: <root>/{DEFAULT_DOCS})")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RULE", help="only run the named rule(s) "
                    "(id or slug; repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--output", default=None,
                    help="also write the json report here (any --format)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    files = _iter_py_files(args.paths)
    if not files:
        print("rtpulint: no python files under " + ", ".join(args.paths),
              file=sys.stderr)
        return 2

    docs_path = args.docs or os.path.join(root, DEFAULT_DOCS)
    docs_text = ""
    if os.path.exists(docs_path):
        with open(docs_path, encoding="utf-8") as fh:
            docs_text = fh.read()
    docs_name = os.path.relpath(docs_path, root).replace(os.sep, "/")

    rules = None
    if args.rule:
        rules = set()
        slugs = {v: k for k, v in RULES.items()}
        for r in args.rule:
            if r not in RULES and r not in slugs:
                print(f"rtpulint: unknown rule {r!r} "
                      f"(known: {', '.join(sorted(RULES))} / "
                      f"{', '.join(sorted(slugs))})", file=sys.stderr)
                return 2
            rules.add(RULES.get(r, r))
            rules.add(slugs.get(r, r))

    findings = analyze_project([_load(f, root) for f in files],
                               docs_text=docs_text, docs_name=docs_name,
                               rules=rules)

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        if args.rule:
            # a filtered run only saw a slice of the findings — writing it
            # would silently drop every other rule's accepted entries
            print("rtpulint: refusing --write-baseline with --rule; "
                  "run the full rule set to regenerate the baseline",
                  file=sys.stderr)
            return 2
        Baseline.from_findings(findings).save(baseline_path)
        print(f"rtpulint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = Baseline()
    baseline_used = False
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = Baseline.load(baseline_path)
        baseline_used = True
    new, accepted, stale = baseline.split(findings)

    report = {
        "tool": "rtpulint",
        "files_scanned": len(files),
        "rules": sorted(RULES.values()),
        "baseline": baseline_path if baseline_used else None,
        "total": len(findings),
        "new": [f.as_dict() for f in new],
        "accepted": [f.as_dict() for f in accepted],
        "stale_baseline_entries": stale,
    }
    if args.output:
        with open(args.output, "w") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")

    if args.format == "json":
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in new:
            print(f.render())
        tail = (f"rtpulint: {len(files)} files, {len(findings)} finding(s): "
                f"{len(new)} new, {len(accepted)} baselined")
        if stale:
            tail += (f", {stale} stale baseline entr"
                     f"{'y' if stale == 1 else 'ies'} (consider "
                     f"--write-baseline)")
        print(tail)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
