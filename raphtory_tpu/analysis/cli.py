"""rtpulint command line — scan, baseline-diff, report.

``tools/rtpulint raphtory_tpu/`` is the CI entry point: exit 0 when every
finding is covered by the checked-in baseline, exit 1 on new findings (or
parse errors), exit 2 on usage errors. ``--write-baseline`` refreshes the
baseline after a reviewed change; ``--format json`` emits the machine
report CI uploads as an artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .findings import Baseline
from .rules import RULES, analyze_project

DEFAULT_BASELINE = os.path.join("tools", "rtpulint_baseline.json")
DEFAULT_DOCS = os.path.join("docs", "OPERATIONS.md")


def _is_python_script(path: str) -> bool:
    """Extensionless executables with a python shebang (tools/rtpulint,
    tools/perfwatch) are source too — the tools/ scan must not skip the
    linter's own drivers."""
    try:
        with open(path, "rb") as fh:
            first = fh.readline(120)
        return first.startswith(b"#!") and b"python" in first
    except OSError:
        return False


def _iter_py_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for f in sorted(filenames):
                full = os.path.join(dirpath, f)
                if f.endswith(".py") or \
                        ("." not in f and _is_python_script(full)):
                    out.append(full)
    return out


def _load(path: str, root: str) -> tuple[str, str]:
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8") as fh:
        return rel.replace(os.sep, "/"), fh.read()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtpulint",
        description="project-specific static analysis for raphtory_tpu "
                    "(rule catalogue: docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="+", help="files or directories to scan")
    ap.add_argument("--root", default=".",
                    help="repo root findings are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline json (default: <root>/{DEFAULT_BASELINE} "
                         f"when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings as the new baseline")
    ap.add_argument("--docs", default=None,
                    help=f"knob-table doc for undocumented-knob "
                         f"(default: <root>/{DEFAULT_DOCS})")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RULE", help="only run the named rule(s) "
                    "(id or slug; repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--output", default=None,
                    help="also write the json report here (any --format)")
    ap.add_argument("--fix", action="store_true",
                    help="apply mechanical autofixes in place (RT008 "
                         "unused-import; idempotent, pragma-respecting) "
                         "before reporting")
    ap.add_argument("--fix-diff", default=None, metavar="PATH",
                    help="write the unified diff --fix WOULD apply to "
                         "PATH without modifying any file (the CI "
                         "suggestion artifact)")
    ap.add_argument("--timings", action="store_true",
                    help="report per-rule wall seconds (text: stderr "
                         "table; always included in the json report)")
    ap.add_argument("--budget-seconds", type=float, default=None,
                    metavar="S", help="fail (exit 1) when the analysis "
                    "itself takes longer than S seconds — the CI proof "
                    "that the interprocedural pass stays fast")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    files = _iter_py_files(args.paths)
    if not files:
        print("rtpulint: no python files under " + ", ".join(args.paths),
              file=sys.stderr)
        return 2

    docs_path = args.docs or os.path.join(root, DEFAULT_DOCS)
    docs_text = ""
    if os.path.exists(docs_path):
        with open(docs_path, encoding="utf-8") as fh:
            docs_text = fh.read()
    docs_name = os.path.relpath(docs_path, root).replace(os.sep, "/")

    rules = None
    if args.rule:
        rules = set()
        slugs = {v: k for k, v in RULES.items()}
        for r in args.rule:
            if r not in RULES and r not in slugs:
                print(f"rtpulint: unknown rule {r!r} "
                      f"(known: {', '.join(sorted(RULES))} / "
                      f"{', '.join(sorted(slugs))})", file=sys.stderr)
                return 2
            rules.add(RULES.get(r, r))
            rules.add(slugs.get(r, r))

    sources = [_load(f, root) for f in files]

    fixed_names = 0
    if args.fix or args.fix_diff:
        from .fixes import fix_files, unified_diff

        fixed, fixed_names = fix_files(sources)
        if args.fix_diff:
            with open(args.fix_diff, "w", encoding="utf-8") as fh:
                for rel in sorted(fixed):
                    old = next(s for r, s in sources if r == rel)
                    fh.write(unified_diff(rel, old, fixed[rel]))
            print(f"rtpulint: wrote fix suggestions for {len(fixed)} "
                  f"file(s) ({fixed_names} import(s)) to {args.fix_diff}",
                  file=sys.stderr)
        if args.fix:
            by_rel = dict(zip([r for r, _ in sources], files))
            for rel, new_src in sorted(fixed.items()):
                with open(by_rel[rel], "w", encoding="utf-8") as fh:
                    fh.write(new_src)
            if fixed:
                print(f"rtpulint: fixed {fixed_names} unused import(s) "
                      f"in {len(fixed)} file(s)", file=sys.stderr)
            # report on the FIXED sources — --fix then exits by what's left
            sources = [(r, fixed.get(r, s)) for r, s in sources]

    timings: dict = {}
    findings = analyze_project(sources,
                               docs_text=docs_text, docs_name=docs_name,
                               rules=rules, timings=timings)
    analysis_seconds = sum(timings.values())

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        if args.rule:
            # a filtered run only saw a slice of the findings — writing it
            # would silently drop every other rule's accepted entries
            print("rtpulint: refusing --write-baseline with --rule; "
                  "run the full rule set to regenerate the baseline",
                  file=sys.stderr)
            return 2
        Baseline.from_findings(findings).save(baseline_path)
        print(f"rtpulint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = Baseline()
    baseline_used = False
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = Baseline.load(baseline_path)
        baseline_used = True
    new, accepted, stale = baseline.split(findings)

    report = {
        "tool": "rtpulint",
        "files_scanned": len(files),
        "rules": sorted(RULES.values()),
        "baseline": baseline_path if baseline_used else None,
        "total": len(findings),
        "new": [f.as_dict() for f in new],
        "accepted": [f.as_dict() for f in accepted],
        "stale_baseline_entries": stale,
        "timings_seconds": {k: round(v, 3)
                            for k, v in sorted(timings.items())},
        "analysis_seconds": round(analysis_seconds, 3),
        "autofixed_imports": fixed_names if args.fix else 0,
    }
    if args.output:
        with open(args.output, "w") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")

    if args.format == "json":
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in new:
            print(f.render())
        tail = (f"rtpulint: {len(files)} files, {len(findings)} finding(s): "
                f"{len(new)} new, {len(accepted)} baselined")
        if stale:
            tail += (f", {stale} stale baseline entr"
                     f"{'y' if stale == 1 else 'ies'} (consider "
                     f"--write-baseline)")
        print(tail)
    if args.timings:
        for rule_id, sec in sorted(timings.items()):
            print(f"rtpulint:   {rule_id:<8} {sec:7.3f}s", file=sys.stderr)
        print(f"rtpulint:   total    {analysis_seconds:7.3f}s",
              file=sys.stderr)
    if args.budget_seconds is not None and \
            analysis_seconds > args.budget_seconds:
        print(f"rtpulint: analysis took {analysis_seconds:.1f}s — over "
              f"the {args.budget_seconds:.0f}s budget", file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
