"""rtpulint — project-specific static analysis + runtime lock sanitizer.

The sweep engines rely on invariants nothing in Python enforces: compiled-
program cache keys must capture every tuning knob, donated buffers must
never be reused, traced code must not sync with the host, and the threaded
ingest/transfer/REST paths must take locks in one global order. Each rule
here encodes one invariant the project has already been bitten by (the
round-5 advisor caught the ``RTPU_TILE_BUDGET_MB``-not-in-cache-key bug and
a bare-``Exception`` retry loop by hand — this package turns those reviews
into CI gates).

Two halves:

* **Static rules** (``rules.py`` per-module, ``interproc.py`` +
  ``concurrency.py`` project-wide) — AST passes run by
  ``tools/rtpulint`` (or ``python -m raphtory_tpu.analysis``) against a
  checked-in baseline so CI fails only on NEW violations. v2 is
  interprocedural: a module-resolving call graph, inferred thread
  roots, and reaching locksets power RT009–RT011 and the cross-module
  halves of RT001/RT003/RT004; ``fixes.py`` adds the RT008 ``--fix``
  autofix. v3 adds the device-contract passes
  (``devicecontract.py``): RT012 collectives under per-process control
  flow, RT013 unstable compile-cache keys, RT014 donated/resident
  buffer escapes, RT015 device ops on the ingest path. Rule catalogue
  and suppression syntax: ``docs/STATIC_ANALYSIS.md``.
* **Runtime sanitizers** (``sanitizer.py``) — ``RTPU_SANITIZE=1`` wraps
  ``threading.Lock``/``RLock`` to build a lock-ordering graph, reports
  cycles (potential deadlocks), locks held across ``device_put`` /
  ``device_get`` / ``block_until_ready`` boundaries, and Eraser-style
  lockset races over registered shared structures (``track_shared``),
  mirroring findings into the ``obs.trace`` flight recorder. The same
  switch arms the mesh-divergence sanitizer: per-process dispatch
  fingerprint rings cross-checked on ``/clusterz``, plus a
  barrier-stall watchdog (``RTPU_SANITIZE_BARRIER_S``). Zero overhead
  when the env var is unset: nothing is patched.
"""

from __future__ import annotations

from .findings import Baseline, Finding
from .rules import RULES, analyze_module, analyze_project
from .sanitizer import (LockSanitizer, MeshSanitizer, install,
                        mesh_active, mesh_prefix_divergence, track_shared,
                        uninstall)

__all__ = [
    "Baseline",
    "Finding",
    "RULES",
    "analyze_module",
    "analyze_project",
    "LockSanitizer",
    "MeshSanitizer",
    "install",
    "mesh_active",
    "mesh_prefix_divergence",
    "track_shared",
    "uninstall",
]
