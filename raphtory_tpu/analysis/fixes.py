"""rtpulint --fix: mechanical autofixes for rules that have exactly one
correct resolution.

Only RT008 (unused-import) is autofixable today: removing a dead import
cannot change behaviour (import side effects notwithstanding — a module
imported ONLY for side effects should be ``import x  # rtpulint:
disable=unused-import``, and pragma'd findings are never fixed).
Fixes are idempotent: a fixed file re-scans clean, so running --fix twice
is a no-op.
"""

from __future__ import annotations

import ast
import difflib
import re

from .rules import Module, _check_unused_import
from .findings import suppressed

_NAME_RE = re.compile(r"^'(?P<name>[^']+)' is imported but never used$")


def _unused_names(mod: Module) -> dict[int, set[str]]:
    """Import-statement lineno → bound names to drop (suppressions
    respected — a pragma'd import is a considered exception, not a fix
    target)."""
    out: dict[int, set[str]] = {}
    for f in _check_unused_import(mod):
        if suppressed(f, mod.pragmas):
            continue
        m = _NAME_RE.match(f.message)
        if m:
            out.setdefault(f.line, set()).add(m.group("name"))
    return out


def _rebuild_import(node, keep: list) -> str:
    """Source text for ``node`` with only the ``keep`` aliases."""
    names = ", ".join(a.name + (f" as {a.asname}" if a.asname else "")
                      for a in keep)
    indent = " " * node.col_offset
    if isinstance(node, ast.ImportFrom):
        dots = "." * node.level
        return f"{indent}from {dots}{node.module or ''} import {names}"
    return f"{indent}import {names}"


def fix_unused_imports(src: str, relpath: str = "<string>") -> tuple[str, int]:
    """(new_source, names_removed). Whole statements whose every alias is
    unused are deleted outright (their line(s) disappear); partially-dead
    statements are rebuilt with the live aliases only. Multi-line
    (parenthesised) imports collapse to one rebuilt line."""
    try:
        mod = Module(path=relpath, relpath=relpath, src=src)
    except SyntaxError:
        return src, 0
    doomed = _unused_names(mod)
    if not doomed:
        return src, 0
    lines = src.splitlines(keepends=True)
    removed = 0
    # group by (start, end): two statements can share one line
    # (`import os; import sys`) — their surviving segments must merge
    # into ONE replacement, not two overlapping edits (the second edit
    # would delete the first's rebuilt line)
    by_span: dict[tuple[int, int], list] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        drop = doomed.get(node.lineno)
        if not drop:
            continue

        def bound_name(a, _node=node):
            if isinstance(_node, ast.Import) and not a.asname:
                return a.name.split(".")[0]
            return a.asname or a.name

        keep = [a for a in node.names if bound_name(a) not in drop]
        removed += len(node.names) - len(keep)
        end = getattr(node, "end_lineno", node.lineno)
        by_span.setdefault((node.lineno, end), []).append((node, keep))
    if removed == 0:
        return src, 0
    edits: list[tuple[int, int, list[str]]] = []
    for (start, end), entries in by_span.items():
        segs = [_rebuild_import(n, keep).lstrip()
                for n, keep in entries if keep]
        if not segs:
            edits.append((start, end, []))
            continue
        raw_last = lines[end - 1]
        nl = "\r\n" if raw_last.endswith("\r\n") else "\n"
        indent = " " * entries[0][0].col_offset
        # a trailing comment on the original line survives the rebuild —
        # it may be a pragma for ANOTHER rule, or a reviewer note
        m = re.search(r"(#.*?)\s*$", raw_last.rstrip("\r\n"))
        comment = f"  {m.group(1)}" if m else ""
        edits.append((start, end,
                      [indent + "; ".join(segs) + comment + nl]))
    for start, end, repl in sorted(edits, reverse=True):
        lines[start - 1: end] = repl
    return "".join(lines), removed


def fix_files(paths_and_sources: list[tuple[str, str]]):
    """[(path, src)] → (fixed {path: new_src}, total names removed).
    Files that need no change are absent from the result dict."""
    fixed: dict[str, str] = {}
    total = 0
    for relpath, src in paths_and_sources:
        new, n = fix_unused_imports(src, relpath)
        if n:
            fixed[relpath] = new
            total += n
    return fixed, total


def unified_diff(relpath: str, old: str, new: str) -> str:
    return "".join(difflib.unified_diff(
        old.splitlines(keepends=True), new.splitlines(keepends=True),
        fromfile=f"a/{relpath}", tofile=f"b/{relpath}"))
