"""Project-wide interprocedural model for the rtpulint rules.

The per-module AST rules (PR 4) stop at module boundaries, which is
exactly where the serving-era bug class lives: a REST handler thread and a
fold worker share engine state through a chain of calls that no single
module shows. This module builds the project-level tables the
interprocedural rules (RT009–RT011, and the cross-module halves of
RT001/RT003/RT004) share:

* **module resolution** — relpath → dotted module name, plus an alias
  table per module covering ``import x.y as z``, ``from ..pkg import mod``
  and ``from .mod import fn`` (function-local imports included: the repo
  imports lazily for jax-stripped environments);
* **call graph** — call expressions resolved to function defs across
  modules: bare names through the local/nested/imported scopes,
  ``alias.fn`` through module aliases, ``self.meth``/``cls.meth`` to the
  enclosing class (never a same-named method elsewhere — the RT003
  scoping lesson), and ``obj.meth`` when ``obj`` is constructed from a
  resolvable class in the same function;
* **thread roots** — where concurrency actually starts:
  ``threading.Thread(target=…)``, ``executor.submit(…)`` (the fold pools),
  ``threading.Timer``, ``do_GET``/``do_POST``-style handlers on
  ``BaseHTTPRequestHandler`` subclasses (``ThreadingHTTPServer`` runs each
  request on its own thread), and ``Gauge.set_function`` callbacks (run on
  the metrics scrape thread);
* **reaching locksets** — a depth-first walk from every thread root that
  tracks the set of locks held at each statement (``with lock:`` blocks,
  plus balanced same-function ``acquire``/``release`` pairs) THROUGH
  calls, memoised on (function, lockset) so shared helpers are walked
  once per distinct context.

Deliberately precision-first: resolution that cannot be done confidently
is skipped, because every false positive here costs a source fix or a
reviewed pragma (the baseline stays empty by policy). stdlib-only, like
the rest of the analysis package.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .rules import Module, _dotted, _parent, _enclosing_def

#: resolution depth bound for call-graph walks — deep enough for the
#: repo's real chains (REST → manager → engine → transfer is 4), bounded
#: so a pathological cycle cannot hang the lint.
MAX_DEPTH = 8

_LOCKY_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore"}
_EXECUTOR_SUBMIT = {"submit"}
_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}
#: container constructors that mark an attribute/global as long-lived
#: mutable state (RT010/RT011 candidates)
CONTAINER_FACTORIES = {"dict", "list", "set", "defaultdict", "deque",
                       "OrderedDict", "Counter", "Queue", "LifoQueue",
                       "PriorityQueue", "SimpleQueue", "WeakKeyDictionary",
                       "WeakValueDictionary"}


def module_name_of(relpath: str) -> str:
    """``raphtory_tpu/jobs/manager.py`` → ``raphtory_tpu.jobs.manager``;
    ``pkg/__init__.py`` → ``pkg``; extensionless scripts keep their stem
    (``tools/rtpulint`` → ``tools.rtpulint``)."""
    p = relpath.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.strip("/").replace("/", ".")


@dataclass
class FuncInfo:
    """One function/method definition plus its project coordinates."""

    mod: Module
    node: ast.FunctionDef
    qualname: str            # within the module, e.g. "FoldCache.get"
    cls: str | None = None   # enclosing class name, if a method

    @property
    def key(self) -> tuple:
        return (self.mod.relpath, self.qualname)

    @property
    def label(self) -> str:
        return f"{module_name_of(self.mod.relpath)}.{self.qualname}"


@dataclass
class ThreadRoot:
    """An inferred concurrency entry point."""

    fn: FuncInfo
    kind: str                # thread | executor | timer | rest-handler |
    #                          scrape-callback
    spawn_site: str = ""     # "relpath:line" of the spawning call ("" for
    #                          handler-class roots)

    @property
    def label(self) -> str:
        return f"{self.fn.label}[{self.kind}]"


class Project:
    """The resolved project: modules, functions, imports, call graph."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.by_name: dict[str, Module] = {}
        for m in modules:
            self.by_name[module_name_of(m.relpath)] = m
        #: (relpath, qualname) → FuncInfo
        self.functions: dict[tuple, FuncInfo] = {}
        #: module name → {bare name → FuncInfo} (module scope defs)
        self.toplevel: dict[str, dict[str, FuncInfo]] = {}
        #: module name → {class name → {method name → FuncInfo}}
        self.classes: dict[str, dict[str, dict[str, FuncInfo]]] = {}
        #: module name → {class name → ClassDef}
        self.class_nodes: dict[str, dict[str, ast.ClassDef]] = {}
        #: module name → {alias → ("module", dotted) | ("symbol", mod, nm)}
        self.imports: dict[str, dict[str, tuple]] = {}
        for m in modules:
            self._index_module(m)
        self._roots: list[ThreadRoot] | None = None

    # ------------------------------------------------------------ indexing

    def _index_module(self, m: Module) -> None:
        name = module_name_of(m.relpath)
        top: dict[str, FuncInfo] = {}
        classes: dict[str, dict[str, FuncInfo]] = {}
        cnodes: dict[str, ast.ClassDef] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn, cls = _qual_and_class(node)
                fi = FuncInfo(m, node, qn, cls)
                self.functions[fi.key] = fi
                parent = _parent(node)
                if isinstance(parent, ast.Module):
                    top[node.name] = fi
                elif isinstance(parent, ast.ClassDef) and \
                        isinstance(_parent(parent), ast.Module):
                    classes.setdefault(parent.name, {})[node.name] = fi
            elif isinstance(node, ast.ClassDef) and \
                    isinstance(_parent(node), ast.Module):
                cnodes[node.name] = node
        self.toplevel[name] = top
        self.classes[name] = classes
        self.class_nodes[name] = cnodes
        self.imports[name] = self._alias_table(m, name)

    def _alias_table(self, m: Module, name: str) -> dict[str, tuple]:
        """All imports in the module (function-local included — the repo
        imports lazily), collapsed into one alias table."""
        out: dict[str, tuple] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = ("module", a.name)
                    else:
                        out[a.name.split(".")[0]] = \
                            ("module", a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                if node.level:
                    base_parts = name.split(".")
                    # level=1: current package; each extra level: one up.
                    # For an __init__.py the dotted name already IS the
                    # package, so one fewer component comes off — else
                    # `from .mod import f` in pkg/__init__.py resolved a
                    # level too high and every re-export chain silently
                    # dropped out of the call graph
                    drop = node.level
                    if m.relpath.replace("\\", "/").endswith(
                            "__init__.py"):
                        drop -= 1
                    base_parts = base_parts[: len(base_parts) - drop] \
                        if drop else base_parts
                    base = ".".join(base_parts)
                else:
                    base = ""
                src = ".".join(p for p in (base, node.module or "") if p)
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    target = f"{src}.{a.name}" if src else a.name
                    if target in self.by_name:
                        out[bound] = ("module", target)
                    else:
                        out[bound] = ("symbol", src, a.name)
        return out

    # ---------------------------------------------------------- resolution

    def resolve_call(self, m: Module, scope, call: ast.Call) -> FuncInfo | None:
        """The FunctionDef a call lands in, or None when resolution is not
        confident. ``scope`` is the enclosing FunctionDef (or None at
        module level)."""
        return self.resolve_target(m, scope, call.func)

    def resolve_target(self, m: Module, scope, func: ast.AST) -> FuncInfo | None:
        name = module_name_of(m.relpath)
        if isinstance(func, ast.Name):
            return self._resolve_bare(m, name, scope, func.id)
        if not isinstance(func, ast.Attribute):
            return None
        dotted = _dotted(func)
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            cls = _enclosing_class(scope)
            if cls is not None:
                fi = self.classes.get(name, {}).get(cls.name, {}) \
                    .get(parts[1])
                if fi is not None:
                    return fi
                # inherited method: single-level, same-project bases only
                for base in cls.bases:
                    bname = _dotted(base).split(".")[-1]
                    for mod2, cmap in self.classes.items():
                        if bname in cmap and parts[1] in cmap[bname]:
                            return cmap[bname][parts[1]]
            return None
        if parts[0] in ("self", "cls") and len(parts) == 3:
            # self.attr.meth() — infer attr's class from a class-level
            # annotation (`manager: AnalysisManager = None`, the REST
            # handler injection idiom) or an `__init__` assignment
            # (`self.graph = TemporalGraph(...)`)
            cls = _enclosing_class(scope)
            if cls is not None:
                hit = self._attr_class_of(name, cls, parts[1])
                if hit is not None:
                    mod2, cname = hit
                    return self.classes.get(mod2, {}).get(cname, {}) \
                        .get(parts[2])
            return None
        binding = self.imports.get(name, {}).get(parts[0])
        if binding is not None and binding[0] == "module":
            target_mod = binding[1]
            rest = parts[1:]
            # walk submodules as far as they exist
            while len(rest) > 1 and f"{target_mod}.{rest[0]}" in self.by_name:
                target_mod = f"{target_mod}.{rest[0]}"
                rest = rest[1:]
            if len(rest) == 1:
                fi = self.toplevel.get(target_mod, {}).get(rest[0])
                if fi is not None:
                    return fi
            if len(rest) == 2:   # alias.Class.method (rare but cheap)
                fi = self.classes.get(target_mod, {}).get(rest[0], {}) \
                    .get(rest[1])
                if fi is not None:
                    return fi
            return None
        # obj.meth where obj is a local constructed from a resolvable
        # class in the same function:  eng = TransferEngine(...); eng.put()
        if scope is not None and len(parts) == 2:
            cls_fi = self._local_class_of(m, name, scope, parts[0])
            if cls_fi is not None:
                mod2, cname = cls_fi
                return self.classes.get(mod2, {}).get(cname, {}) \
                    .get(parts[1])
        return None

    def _resolve_bare(self, m: Module, name: str, scope,
                      bare: str) -> FuncInfo | None:
        # nested def in the enclosing function chain wins
        cur = scope
        while cur is not None:
            for node in ast.walk(cur):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == bare and _enclosing_def(node) is cur:
                    return self.functions.get((m.relpath,
                                               _qual_and_class(node)[0]))
            cur = _enclosing_def(cur)
        fi = self.toplevel.get(name, {}).get(bare)
        if fi is not None:
            return fi
        binding = self.imports.get(name, {}).get(bare)
        if binding is not None:
            if binding[0] == "symbol":
                _, mod2, nm = binding
                fi = self.toplevel.get(mod2, {}).get(nm)
                if fi is not None:
                    return fi
                # imported class: a call constructs it — resolve __init__
                if nm in self.classes.get(mod2, {}):
                    return self.classes[mod2][nm].get("__init__")
            elif binding[0] == "module":
                return None
        # class constructed by bare name in this module
        if bare in self.classes.get(name, {}):
            return self.classes[name][bare].get("__init__")
        return None

    def _attr_class_of(self, mod_name: str, cls: ast.ClassDef,
                       attr: str) -> tuple | None:
        """(module, class) of ``self.<attr>`` on ``cls``, from a class-
        level annotation or a single unambiguous ``__init__``
        construction."""

        def resolve_cname(cname: str) -> tuple | None:
            if cname in self.classes.get(mod_name, {}) or \
                    cname in self.class_nodes.get(mod_name, {}):
                return (mod_name, cname)
            binding = self.imports.get(mod_name, {}).get(cname)
            if binding is not None and binding[0] == "symbol" and \
                    binding[2] in self.classes.get(binding[1], {}):
                return (binding[1], binding[2])
            return None

        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    stmt.target.id == attr:
                cname = _dotted(stmt.annotation).split(".")[-1]
                hit = resolve_cname(cname)
                if hit is not None:
                    return hit
        init = self.classes.get(mod_name, {}).get(cls.name, {}) \
            .get("__init__")
        if init is not None:
            found = None
            for node in ast.walk(init.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and any(
                            isinstance(t, ast.Attribute) and
                            t.attr == attr and _dotted(t.value) == "self"
                            for t in node.targets):
                    hit = resolve_cname(
                        _dotted(node.value.func).split(".")[-1])
                    if hit is None:
                        return None
                    if found is not None and found != hit:
                        return None
                    found = hit
            return found
        return None

    def _local_class_of(self, m: Module, name: str, scope,
                        var: str) -> tuple | None:
        """(module, class) the local ``var`` was constructed from, when a
        single unambiguous ``var = ClassName(...)`` exists in ``scope``."""
        found = None
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == var:
                if not isinstance(node.value, ast.Call):
                    return None
                cname = _dotted(node.value.func).split(".")[-1]
                hit = None
                if cname in self.classes.get(name, {}):
                    hit = (name, cname)
                else:
                    binding = self.imports.get(name, {}).get(cname)
                    if binding is not None and binding[0] == "symbol" and \
                            cname in self.classes.get(binding[1], {}):
                        hit = (binding[1], cname)
                if hit is None:
                    return None
                if found is not None and found != hit:
                    return None   # ambiguous rebinding
                found = hit
        return found

    # --------------------------------------------------------- thread roots

    def thread_roots(self) -> list[ThreadRoot]:
        """Every inferred concurrency entry point. All roots are treated
        as multi-instance: REST handlers run per connection, executors
        run per submit, and the repo spawns its job/ingest threads in
        loops — two instances of one root already race each other."""
        if self._roots is not None:
            return self._roots
        roots: dict[tuple, ThreadRoot] = {}

        def add(fi: FuncInfo | None, kind: str, site: str) -> None:
            if fi is not None:
                roots.setdefault((fi.key, kind),
                                 ThreadRoot(fi, kind, site))

        for m in self.modules:
            name = module_name_of(m.relpath)
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call):
                    scope = _enclosing_def(node)
                    site = f"{m.relpath}:{getattr(node, 'lineno', 0)}"
                    callee = _dotted(node.func)
                    tail = callee.split(".")[-1]
                    if tail in ("Thread", "Timer"):
                        target = _kwarg(node, "target")
                        if target is None and tail == "Timer" and \
                                len(node.args) >= 2:
                            target = node.args[1]
                        add(self._as_func(m, scope, target),
                            "thread" if tail == "Thread" else "timer", site)
                    elif tail in _EXECUTOR_SUBMIT and node.args:
                        add(self._as_func(m, scope, node.args[0]),
                            "executor", site)
                    elif tail == "set_function" and node.args:
                        add(self._as_func(m, scope, node.args[0]),
                            "scrape-callback", site)
            # request-handler classes: each do_* method is a root
            for cname, cnode in self.class_nodes.get(name, {}).items():
                if not self._is_handler_class(name, cnode):
                    continue
                for meth, fi in self.classes[name].get(cname, {}).items():
                    if meth.startswith("do_"):
                        add(fi, "rest-handler", "")
        self._roots = sorted(roots.values(), key=lambda r: r.label)
        return self._roots

    def _is_handler_class(self, mod_name: str, cnode: ast.ClassDef,
                          depth: int = 0) -> bool:
        for base in cnode.bases:
            bname = _dotted(base).split(".")[-1]
            if bname in _HANDLER_BASES:
                return True
            if depth < 3:
                parent = self.class_nodes.get(mod_name, {}).get(bname)
                if parent is None:
                    binding = self.imports.get(mod_name, {}).get(bname)
                    if binding is not None and binding[0] == "symbol":
                        parent = self.class_nodes.get(binding[1], {}) \
                            .get(binding[2])
                        mod_name2 = binding[1]
                    else:
                        parent, mod_name2 = None, mod_name
                else:
                    mod_name2 = mod_name
                if parent is not None and \
                        self._is_handler_class(mod_name2, parent, depth + 1):
                    return True
        return False

    def _as_func(self, m: Module, scope, expr) -> FuncInfo | None:
        if expr is None:
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return self.resolve_target(m, scope, expr)
        if isinstance(expr, ast.Lambda):
            return None
        return None

    # ------------------------------------------------------------- walking

    def walk_from(self, start: FuncInfo, visit,
                  lockset: frozenset = frozenset(),
                  follow_spawns: bool = False, max_depth: int = MAX_DEPTH,
                  follow_filter=None, seen: set | None = None):
        """Depth-first interprocedural walk from ``start``.

        ``visit(fn, node, lockset, chain)`` is called for every AST node
        of every function reached, with the lockset held at that node and
        the call chain (tuple of FuncInfo) that got there. Memoised on
        (function, lockset): a helper reached under two different locksets
        is walked once per distinct context; pass a shared ``seen`` set to
        extend the memo across walks (how the RT009 all-functions sweep
        stays linear). ``follow_filter(callee) -> bool`` vetoes descent
        into particular callees (RT001 does not enter other cached
        factories — their cache key is their own rule instance). When
        ``follow_spawns`` is true, thread/executor targets spawned along
        the way are walked too, with an EMPTY lockset — the new thread
        holds nothing — which is how "request-reachable" crosses the
        submit-a-job boundary."""
        if seen is None:
            seen = set()

        def go(fn: FuncInfo, locks: frozenset, chain: tuple, depth: int):
            if depth > max_depth or (fn.key, locks) in seen:
                return
            if chain and follow_filter is not None and \
                    not follow_filter(fn):
                return
            seen.add((fn.key, locks))
            chain = chain + (fn,)
            self._walk_body(fn, list(fn.node.body), locks, chain, visit,
                            go, depth, follow_spawns)

        go(start, lockset, (), 0)

    def _walk_body(self, fn: FuncInfo, stmts, locks: frozenset, chain,
                   visit, go, depth: int, follow_spawns: bool) -> None:
        """Statement-structured walk: each expression node is visited
        exactly once, with the lockset actually held at that statement.
        Explicit ``X.acquire()``/``X.release()`` statements adjust the set
        for the REST of the enclosing body (cross-function hand-offs —
        acquire here, release in the caller — are out of scope and stay
        invisible, documented in docs/STATIC_ANALYSIS.md)."""
        args = (chain, visit, go, depth, follow_spawns)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # nested defs are walked when called
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new = set(locks)
                for item in stmt.items:
                    self._visit_expr(fn, item.context_expr, locks, *args)
                    lid = self._lock_id(fn, item.context_expr)
                    if lid is not None:
                        new.add(lid)
                self._walk_body(fn, stmt.body, frozenset(new), *args)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._visit_expr(fn, stmt.iter, locks, *args)
                self._walk_body(fn, stmt.body + stmt.orelse, locks, *args)
                continue
            if isinstance(stmt, ast.While):
                self._visit_expr(fn, stmt.test, locks, *args)
                self._walk_body(fn, stmt.body + stmt.orelse, locks, *args)
                continue
            if isinstance(stmt, ast.If):
                self._visit_expr(fn, stmt.test, locks, *args)
                self._walk_body(fn, stmt.body + stmt.orelse, locks, *args)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_body(fn, stmt.body, locks, *args)
                for h in stmt.handlers:
                    self._walk_body(fn, h.body, locks, *args)
                self._walk_body(fn, stmt.orelse, locks, *args)
                self._walk_body(fn, stmt.finalbody, locks, *args)
                continue
            # explicit acquire()/release() as bare statements
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr in ("acquire", "release"):
                lid = self._lock_id(fn, stmt.value.func.value)
                if lid is not None:
                    locks = (locks | {lid}
                             if stmt.value.func.attr == "acquire"
                             else locks - {lid})
                self._visit_expr(fn, stmt, locks, *args)
                continue
            self._visit_expr(fn, stmt, locks, *args)

    def _visit_expr(self, fn: FuncInfo, node: ast.AST, locks: frozenset,
                    chain, visit, go, depth: int,
                    follow_spawns: bool) -> None:
        for sub in ast.walk(node):
            visit(fn, sub, locks, chain)
            if isinstance(sub, ast.Call):
                self._follow_call(fn, sub, locks, chain, go, depth,
                                  follow_spawns)

    def _follow_call(self, fn: FuncInfo, node: ast.Call, locks, chain, go,
                     depth: int, follow_spawns: bool) -> None:
        callee = self.resolve_call(fn.mod, _enclosing_def(node), node)
        if callee is not None and callee.node is not fn.node:
            go(callee, locks, chain, depth + 1)
        if follow_spawns:
            tail = _dotted(node.func).split(".")[-1]
            target = None
            if tail in ("Thread", "Timer"):
                target = _kwarg(node, "target")
                if target is None and tail == "Timer" and \
                        len(node.args) >= 2:
                    target = node.args[1]
            elif tail in _EXECUTOR_SUBMIT and node.args:
                target = node.args[0]
            if target is not None:
                tfi = self._as_func(fn.mod, _enclosing_def(node), target)
                if tfi is not None:
                    # the spawned thread starts with nothing held
                    go(tfi, frozenset(), chain, depth + 1)

    # ------------------------------------------------------------ lock ids

    def _lock_id(self, fn: FuncInfo, expr: ast.AST) -> str | None:
        """Stable identity for a lock expression, or None when the
        expression is not confidently a lock. ``module.NAME`` for module
        globals, ``module.Class.attr`` for instance locks."""
        if isinstance(expr, ast.Call):
            expr = expr.func
            # with lock_for(x): … — a call RETURNING a lock: identify by
            # the callee (one id per factory — conservative but stable)
            dotted = _dotted(expr)
            if dotted and _looks_locky(dotted.split(".")[-1]):
                return f"{fn.mod.relpath}:{dotted}()"
            return None
        dotted = _dotted(expr)
        if not dotted:
            return None
        name = module_name_of(fn.mod.relpath)
        parts = dotted.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            cls = _enclosing_class(fn.node)
            cname = cls.name if cls is not None else "?"
            if _looks_locky(parts[1]) or self._attr_is_lock(name, cname,
                                                            parts[1]):
                return f"{name}.{cname}.{parts[1]}"
            return None
        if len(parts) == 1:
            if self._global_is_lock(name, parts[0]):
                return f"{name}.{parts[0]}"
            if _looks_locky(parts[0]):
                # a local bound to a lock (lock = self._mu; with lock:) —
                # identify per function, best effort
                return f"{fn.mod.relpath}:{fn.qualname}:{parts[0]}"
            return None
        if _looks_locky(parts[-1]):
            return f"{name}.{dotted}"
        return None

    def _global_is_lock(self, mod_name: str, var: str) -> bool:
        m = self.by_name.get(mod_name)
        if m is None:
            return False
        for stmt in getattr(m.tree, "body", []):
            if isinstance(stmt, ast.Assign) and \
                    any(isinstance(t, ast.Name) and t.id == var
                        for t in stmt.targets) and \
                    isinstance(stmt.value, ast.Call) and \
                    _dotted(stmt.value.func).split(".")[-1] in \
                    _LOCKY_FACTORIES:
                return True
        return False

    def _attr_is_container(self, mod_name: str, cname: str,
                           attr: str) -> bool:
        """True when ``self.<attr>`` is assigned a mutable container in
        the class's ``__init__`` — the long-lived-state candidate set for
        RT010/RT011."""
        init = self.classes.get(mod_name, {}).get(cname, {}).get("__init__")
        if init is None:
            return False
        for node in ast.walk(init.node):
            targets, value = [], None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            is_container = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                              ast.ListComp, ast.DictComp,
                                              ast.SetComp))
            if isinstance(value, ast.Call):
                is_container = _dotted(value.func).split(".")[-1] in \
                    CONTAINER_FACTORIES
            if not is_container:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == attr and \
                        _dotted(t.value) == "self":
                    return True
        return False

    def _attr_is_lock(self, mod_name: str, cname: str, attr: str) -> bool:
        init = self.classes.get(mod_name, {}).get(cname, {}).get("__init__")
        if init is None:
            return False
        for node in ast.walk(init.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _dotted(node.value.func).split(".")[-1] in \
                    _LOCKY_FACTORIES:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == attr and \
                            _dotted(t.value) == "self":
                        return True
        return False


# ---------------------------------------------------------------- helpers


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _looks_locky(name: str) -> bool:
    low = name.lower()
    return ("lock" in low or "mutex" in low or low in ("_mu", "mu", "cv")
            or "cond" in low)


def _qual_and_class(node) -> tuple[str, str | None]:
    names, cls = [], None
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
            if isinstance(cur, ast.ClassDef) and cls is None and \
                    cur is not node:
                cls = cur.name
        cur = _parent(cur)
    return ".".join(reversed(names)), cls


def _enclosing_class(scope) -> ast.ClassDef | None:
    cur = scope
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = _parent(cur)
    return None
