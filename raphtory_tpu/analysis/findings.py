"""Finding / suppression / baseline plumbing for the static rules.

Findings carry a *fingerprint* — a hash of (rule, file, enclosing symbol,
normalized source line) that deliberately excludes the line NUMBER, so code
motion above a known violation does not churn the baseline. The baseline
is multiset-semantic: two identical lines in one function are two entries,
and a third copy is a new finding.
"""

from __future__ import annotations

import hashlib
import json
import re
from collections import Counter
from dataclasses import dataclass, field

#: inline pragma: ``# rtpulint: disable=rule-a,RT002`` — suppresses matching
#: findings on the SAME line, or (as a standalone comment) on the next line.
_PRAGMA = re.compile(r"#\s*rtpulint:\s*disable=([A-Za-z0-9_,\-\s]+)")

#: SPMD-uniformity declaration: ``# rtpulint: spmd-uniform -- <why>``.
#: Unlike ``disable=``, this is an ASSERTION with a mandatory justification
#: — RT012 refuses to honour one whose justification is empty, so every
#: silenced divergence site carries its reviewed uniformity argument in
#: the source.
_SPMD_UNIFORM = re.compile(
    r"#\s*rtpulint:\s*spmd-uniform\b[\s:—–-]*(.*)$")


@dataclass
class Finding:
    rule: str           # rule id, e.g. "RT001"
    name: str           # rule slug, e.g. "env-not-in-cache-key"
    path: str           # path relative to the scan root
    line: int           # 1-based
    col: int
    message: str
    symbol: str = ""    # enclosing function qualname ("" at module level)
    line_text: str = ""

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.line_text.split())
        raw = "\0".join((self.rule, self.path, self.symbol, norm))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.name}: {self.message}{sym}")

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "name": self.name, "path": self.path,
            "line": self.line, "col": self.col, "message": self.message,
            "symbol": self.symbol, "fingerprint": self.fingerprint,
        }


def parse_suppressions(lines: list[str]) -> dict[int, set[str]]:
    """1-based line → set of suppressed rule ids/slugs (lowercased).

    A pragma on a code line covers that line; a pragma on a comment-only
    line covers the next line (for lines too long to annotate inline).
    """
    out: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        rules = {r.strip().lower() for r in m.group(1).split(",") if r.strip()}
        target = i + 1 if text.lstrip().startswith("#") else i
        out.setdefault(target, set()).update(rules)
    return out


def parse_spmd_uniform(lines: list[str]) -> dict[int, str]:
    """1-based line → justification text for ``spmd-uniform`` pragmas
    (``""`` when the author wrote none — the caller must treat that as
    NOT suppressed). Same placement semantics as ``disable=``: a pragma
    on a code line covers that line, a comment-only pragma line covers
    the next line."""
    out: dict[int, str] = {}
    for i, text in enumerate(lines, start=1):
        m = _SPMD_UNIFORM.search(text)
        if not m:
            continue
        target = i + 1 if text.lstrip().startswith("#") else i
        out[target] = m.group(1).strip()
    return out


def suppressed(f: Finding, pragmas: dict[int, set[str]]) -> bool:
    rules = pragmas.get(f.line)
    if not rules:
        return False
    return bool(rules & {f.rule.lower(), f.name.lower(), "all"})


@dataclass
class Baseline:
    """Checked-in set of accepted findings; CI fails only on NEW ones."""

    counts: Counter = field(default_factory=Counter)
    entries: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as fh:
            doc = json.load(fh)
        entries = doc.get("findings", [])
        return cls(Counter(e["fingerprint"] for e in entries), entries)

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        # parse errors are never baselinable — accepting one would leave a
        # file permanently unscanned while CI stays green
        findings = [f for f in findings if f.rule != "RT000"]
        return cls(Counter(f.fingerprint for f in findings),
                   [f.as_dict() for f in findings])

    def save(self, path: str) -> None:
        doc = {
            "version": 1,
            "tool": "rtpulint",
            "note": ("accepted findings — regenerate with "
                     "`tools/rtpulint raphtory_tpu/ --write-baseline` "
                     "after reviewing every new entry"),
            "findings": sorted(self.entries, key=lambda e: (
                e["path"], e["rule"], e["line"])),
        }
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=False)
            fh.write("\n")

    def split(self, findings: list[Finding]):
        """(new, accepted, stale_count): multiset-diff current findings
        against the baseline."""
        budget = Counter(self.counts)
        new, accepted = [], []
        for f in findings:
            if f.rule == "RT000":
                new.append(f)   # a hand-edited baseline entry cannot
                continue        # launder a parse error either
            if budget[f.fingerprint] > 0:
                budget[f.fingerprint] -= 1
                accepted.append(f)
            else:
                new.append(f)
        stale = sum(budget.values())
        return new, accepted, stale
