"""``python -m raphtory_tpu.analysis`` — same entry as ``tools/rtpulint``."""

import sys

from .cli import main

sys.exit(main())
