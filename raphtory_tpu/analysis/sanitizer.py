"""Runtime lock sanitizer — deadlock-order and device-boundary findings.

``RTPU_SANITIZE=1`` (checked once, in ``raphtory_tpu/__init__``) wraps the
``threading.Lock`` / ``threading.RLock`` factories so every lock created
afterwards is tracked:

* **lock-order-cycle** — each acquisition with other locks held adds
  held→acquired edges to a process-wide lock-ordering graph; the first
  edge that closes a cycle (A taken under B somewhere, B taken under A
  elsewhere) is a potential deadlock and is reported ONCE per edge with
  both creation sites and both acquisition stacks.
* **lock-across-device-boundary** — ``jax.device_put`` / ``device_get`` /
  ``jax.block_until_ready`` / compiled-program dispatch can block for
  seconds on a busy or flapping interconnect; holding any sanitized lock
  across those boundaries stalls every thread queued on it (the ingest
  writer blocking REST reads is the motivating shape). The sanitizer
  patches all three module-level entry points when jax is importable and
  reports a held-lock set at each crossing. (The ``.block_until_ready()``
  METHOD on arrays is a C type slot and cannot be patched — the static
  RT009 rule covers that spelling at lint time.)
* **shared-state-race** — an Eraser-style lockset detector over
  REGISTERED shared structures (the job table, the fold cache, the
  kernel registry, the transfer stats). Each structure's candidate
  lockset starts as the lockset of the first post-single-threaded
  access and is intersected with the locks held at every later access;
  the moment a second thread is involved, a write under an EMPTY
  candidate set is a data race and is reported once per structure, keyed
  by the registration (creation) site. Single-threaded init stays
  lock-free legitimately: refinement only starts when a second thread
  shows up, exactly like the original Eraser state machine.

Findings go three ways: a ``logging`` warning, an in-process list
(``findings()``, what tests assert on), and an ``obs.trace`` instant so
the flight recorder timeline shows the hazard between the spans that
caused it.

``RTPU_SANITIZE=1`` also arms the **mesh-divergence sanitizer**
(:class:`MeshSanitizer`) — the runtime half of the static RT012 rule.
Every mesh dispatch appends a fingerprint ``(site, route, shape
signature, dtype, superstep sequence number)`` to a bounded per-process
ring and journals it as a ``mesh`` record (``obs/journal``), so two
processes' dispatch prefixes can be cross-checked on ``/clusterz`` and
in ``rtpu-postmortem reconstruct``: the FIRST sequence number whose
fingerprints disagree names the exact collective where the SPMD
programs diverged, with both processes' fingerprints side by side. A
barrier-wait watchdog (``RTPU_SANITIZE_BARRIER_S``) turns the symptom
of divergence — one process waiting forever in ``comm.barrier_wait``
for a collective its peer never issued — into a finding plus a flight
recorder instant WHILE the process is still hung.

Zero overhead when disabled: nothing is imported or patched unless
``install()`` runs, and ``threading.Lock`` stays the pristine C factory.
The mesh hooks (``note_mesh_dispatch``/``mesh_barrier_watch``) cost one
module-global falsy check when the mesh sanitizer is not installed.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import traceback

_log = logging.getLogger("raphtory_tpu.analysis.sanitizer")

#: pristine factories, captured at import so install/uninstall can swap
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock


def _creation_site() -> str:
    """file:line of the frame that called Lock()/RLock(), skipping this
    module's own frames. Raw frame walk, NOT traceback.extract_stack:
    extract_stack touches linecache (file I/O) and costs ~1 ms — and the
    thread pools create a Condition (= a tracked lock) per Future, so a
    parallel fold paid that millisecond hundreds of times per sweep
    (measured: the bulk of a 52%% sanitizer overhead; the frame walk
    brings lock creation back to microseconds)."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename.endswith("sanitizer.py"):
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class _TrackedLock:
    """Proxy over a raw lock that reports acquisition order to the
    sanitizer. Supports the full Lock/RLock surface the codebase uses,
    including being wrapped by ``threading.Condition``."""

    def __init__(self, san: "LockSanitizer", raw, reentrant: bool):
        self._san = san
        self._raw = raw
        self._reentrant = reentrant
        self.site = _creation_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # try-locks with fallback are a legitimate cycle-avoidance
            # idiom — only blocking acquires add ordering edges
            self._san._before_acquire(self)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._san._note_acquired(self)
        return got

    def release(self):
        self._san._note_released(self)
        return self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # threading.Condition(lock) probes _release_save/_acquire_restore/
        # _is_owned with try/except AttributeError to distinguish RLock
        # from Lock — delegation must preserve that (raising here when the
        # RAW lock lacks the attr), while keeping the held-stack honest
        # when Condition.wait releases/reacquires around the sleep
        raw_attr = getattr(self._raw, name)   # AttributeError propagates
        if name == "_release_save":
            def _release_save():
                self._san._note_released(self)
                return raw_attr()
            return _release_save
        if name == "_acquire_restore":
            def _acquire_restore(state):
                raw_attr(state)
                self._san._note_acquired(self)
            return _acquire_restore
        return raw_attr

    def __repr__(self):
        return f"<TrackedLock {self.site} over {self._raw!r}>"


class SharedTracker:
    """One registered shared structure for the Eraser-style lockset race
    detector. Instrumented code calls :meth:`read`/:meth:`write` at its
    access sites (inside whatever lock it holds); the sanitizer walks the
    Eraser state machine:

    ``virgin`` → ``exclusive(t1)`` on first access → ``shared`` (second
    thread reads) / ``shared_modified`` (write with ≥2 threads involved).
    The candidate lockset is initialised when the second thread arrives
    and intersected on every later access; an empty candidate set in
    ``shared_modified`` is a race, reported ONCE per tracker, keyed by
    the registration (creation) site.
    """

    __slots__ = ("san", "name", "site", "state", "owner", "lockset",
                 "reported")

    def __init__(self, san: "LockSanitizer", name: str):
        self.san = san
        self.name = name
        self.site = _creation_site()
        self.state = "virgin"
        self.owner = None          # thread ident while exclusive
        self.lockset: frozenset | None = None   # candidate set
        self.reported = False

    def read(self) -> None:
        self.san._shared_access(self, write=False)

    def write(self) -> None:
        self.san._shared_access(self, write=True)


class LockSanitizer:
    """Lock-ordering graph + device-boundary watcher + lockset races.

    One instance is installed process-wide via :func:`install`; tests build
    private instances and call :meth:`install`/:meth:`uninstall` directly.
    """

    def __init__(self, tracer=None):
        # bookkeeping must use the RAW factory: a tracked internal lock
        # would recurse into its own sanitizer
        self._mu = _RAW_LOCK()
        self._local = threading.local()
        # race detection needs a thread token that is NEVER reused:
        # get_ident() recycles a joined thread's id, which can leave the
        # Eraser machine stuck in `exclusive` when writer B inherits
        # writer A's ident (observed as a flaky missed race)
        import itertools

        self._tid_counter = itertools.count(1)
        #: site → set of sites acquired while this one was held
        self._edges: dict[str, set] = {}
        #: (from, to) edges already reported (report each hazard once)
        self._reported: set = set()
        self._findings: list[dict] = []
        self._shared: list[SharedTracker] = []
        self._installed = False
        self._jax_patched = False
        self._raw_jax: dict = {}
        self._tracer = tracer

    # ---- install / uninstall ----

    def install(self, patch_jax: bool = True) -> "LockSanitizer":
        """Swap the ``threading`` factories for tracking wrappers. Locks
        created BEFORE install stay untracked (import early). Install is
        NESTING-AWARE: the previous factories are captured and restored
        by :meth:`uninstall` — a test's private sanitizer installed on
        top of the process-wide ``RTPU_SANITIZE`` one must hand the
        factories BACK to it, not to the raw C implementations (restoring
        raw mid-suite left every later-created lock untracked, which the
        race detector then read as lock-free access)."""
        if self._installed:
            return self
        self._installed = True
        self._prev_lock = prev_lock = threading.Lock
        self._prev_rlock = prev_rlock = threading.RLock
        san = self

        # wrap the PREVIOUS factory, not the raw one: under a nested
        # install the inner tracked lock keeps reporting to the outer
        # sanitizer too, so the process-wide one never loses coverage
        def make_lock():
            return _TrackedLock(san, prev_lock(), reentrant=False)

        def make_rlock():
            return _TrackedLock(san, prev_rlock(), reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        if patch_jax:
            self._patch_jax()
        _log.info("lock sanitizer installed (RTPU_SANITIZE)")
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = getattr(self, "_prev_lock", _RAW_LOCK)
        threading.RLock = getattr(self, "_prev_rlock", _RAW_RLOCK)
        self._unpatch_jax()
        self._installed = False

    #: module-level jax entry points that can block on the interconnect —
    #: each gets the same held-locks check (the array METHOD
    #: ``.block_until_ready()`` is a C slot; rtpulint RT009 covers that
    #: spelling statically)
    _JAX_BOUNDARIES = ("device_put", "device_get", "block_until_ready")

    def _patch_jax(self) -> None:
        try:
            import jax
        except Exception:
            return   # stripped environment: lock-order checking still works
        san = self
        self._raw_jax = {}
        for name in self._JAX_BOUNDARIES:
            raw = getattr(jax, name, None)
            if raw is None:
                continue

            def checked(*args, __raw=raw, __name=name, **kwargs):
                san.check_boundary(__name)
                return __raw(*args, **kwargs)

            self._raw_jax[name] = raw
            setattr(jax, name, checked)
        self._jax_patched = True

    def _unpatch_jax(self) -> None:
        if self._jax_patched:
            import jax

            for name, raw in self._raw_jax.items():
                setattr(jax, name, raw)
            self._raw_jax = {}
            self._jax_patched = False

    # ---- per-thread held stack ----

    def _held(self) -> list:
        st = getattr(self._local, "held", None)
        if st is None:
            st = self._local.held = []
        return st

    # ---- acquisition hooks ----

    def _before_acquire(self, lock: _TrackedLock) -> None:
        held = self._held()
        if not held:
            return
        if lock._reentrant and any(h is lock for h in held):
            return   # RLock re-entry adds no ordering constraint
        for h in held:
            if h is lock:
                continue
            self._add_edge(h, lock)

    def _note_acquired(self, lock: _TrackedLock) -> None:
        self._held().append(lock)

    def _note_released(self, lock: _TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # ---- ordering graph ----

    def _add_edge(self, frm: _TrackedLock, to: _TrackedLock) -> None:
        a, b = frm.site, to.site
        if a == b:
            return   # two locks from one construction site (e.g. a pool)
        with self._mu:
            fresh = b not in self._edges.get(a, ())
            if fresh:
                self._edges.setdefault(a, set()).add(b)
            cycle = self._find_path(b, a) if fresh else None
        if cycle:
            # path is b→…→a; the new a→b edge closes it — report each
            # participating site once
            self._report_cycle([a] + cycle[:-1])

    def _find_path(self, start: str, goal: str):
        """DFS path start→…→goal in the edge graph (caller holds _mu),
        or None. A found path plus the new goal→start edge is a cycle."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_cycle(self, sites: list[str]) -> None:
        key = ("cycle", frozenset(sites))
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
        finding = {
            "kind": "lock-order-cycle",
            "sites": sites,
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=12)[:-3]),
        }
        self._emit(finding,
                   "potential deadlock: lock-order cycle %s",
                   " -> ".join(sites + [sites[0]]))

    # ---- device boundary ----

    def check_boundary(self, boundary: str) -> None:
        """Report any sanitized locks the calling thread holds while
        crossing ``boundary`` (device_put, compile, dispatch…). Public so
        engine code can mark additional boundaries explicitly."""
        held = [h.site for h in self._held()]
        if not held:
            return
        key = (boundary, tuple(held))
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
        finding = {
            "kind": "lock-across-device-boundary",
            "boundary": boundary,
            "held": held,
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=12)[:-3]),
        }
        self._emit(finding,
                   "lock(s) %s held across %s — a slow interconnect stalls "
                   "every thread queued on them", held, boundary)

    # ---- lockset race detector (Eraser) ----

    def register_shared(self, name: str) -> SharedTracker:
        """Register one shared structure for lockset race detection.
        Call at construction time (the creation site keys the reports);
        instrument access sites with ``tracker.read()``/``.write()``."""
        tracker = SharedTracker(self, name)
        with self._mu:
            self._shared.append(tracker)
        return tracker

    def shared_trackers(self) -> list[SharedTracker]:
        with self._mu:
            return list(self._shared)

    def _tid(self) -> int:
        """Per-thread token, unique for the sanitizer's lifetime (next()
        on a count is atomic under the GIL)."""
        tid = getattr(self._local, "tid", None)
        if tid is None:
            tid = self._local.tid = next(self._tid_counter)
        return tid

    def _shared_access(self, t: SharedTracker, write: bool) -> None:
        me = self._tid()
        held = self._held()
        locks = frozenset(id(h) for h in held)
        sites = sorted(h.site for h in held)
        report = False
        with self._mu:
            if t.state == "virgin":
                t.state, t.owner = "exclusive", me
            elif t.state == "exclusive":
                if t.owner == me:
                    pass   # still single-threaded: init stays lock-free
                else:
                    # second thread: refinement starts HERE
                    t.state = "shared_modified" if write else "shared"
                    t.lockset = locks
            else:
                t.lockset = locks if t.lockset is None \
                    else (t.lockset & locks)
                if write:
                    t.state = "shared_modified"
            if t.state == "shared_modified" and not t.lockset and \
                    not t.reported:
                t.reported = True
                report = True
        if report:
            finding = {
                "kind": "shared-state-race",
                "name": t.name,
                "site": t.site,
                "access": "write" if write else "read",
                "held": sites,
                "thread": threading.current_thread().name,
                "stack": "".join(traceback.format_stack(limit=12)[:-3]),
            }
            self._emit(finding,
                       "shared structure %r (registered at %s) accessed "
                       "from multiple threads with an empty common "
                       "lockset — data race", t.name, t.site)

    # ---- reporting ----

    def _emit(self, finding: dict, msg: str, *fmt) -> None:
        with self._mu:
            self._findings.append(finding)
        _log.warning("sanitizer: " + msg, *fmt)
        tracer = self._tracer
        if tracer is None:
            try:
                from ..obs.trace import TRACER as tracer
            except Exception:
                tracer = False
            self._tracer = tracer
        if tracer:
            attrs = {k: v for k, v in finding.items() if k != "stack"}
            # "name" would collide with Tracer.instant's own first param
            if "name" in attrs:
                attrs["shared_name"] = attrs.pop("name")
            attrs["sites"] = ",".join(
                finding.get("sites") or finding.get("held") or [])
            tracer.instant("sanitizer." + finding["kind"], **attrs)

    def findings(self, kind: str | None = None) -> list[dict]:
        with self._mu:
            out = list(self._findings)
        if kind:
            out = [f for f in out if f["kind"] == kind]
        return out

    def clear(self) -> None:
        with self._mu:
            self._findings.clear()
            self._reported.clear()
            self._edges.clear()
            for t in self._shared:   # re-arm the race detector too
                t.state, t.owner = "virgin", None
                t.lockset, t.reported = None, False


#: the process-wide instance, set by install()
_ACTIVE: LockSanitizer | None = None


def install(patch_jax: bool = True) -> LockSanitizer:
    """Install (or return) the process-wide sanitizer."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = LockSanitizer()
    _ACTIVE.install(patch_jax=patch_jax)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.uninstall()
        _ACTIVE = None


def active() -> LockSanitizer | None:
    return _ACTIVE


def note_shared(tracker: SharedTracker | None, write: bool = False) -> None:
    """One-line access hook for instrumented structures: no-op on the
    None tracker the unsanitized path carries (a single falsy check —
    the zero-overhead-when-unset contract, shared by every registered
    structure instead of re-implemented per class)."""
    if tracker is not None:
        (tracker.write if write else tracker.read)()


def track_shared(name: str) -> SharedTracker | None:
    """Register ``name`` with the ACTIVE sanitizer, or None when no
    sanitizer is installed — the instrumentation contract: call sites
    keep a tracker attribute and guard every ``read()``/``write()`` with
    ``if tracker is not None``, so the unsanitized cost is one falsy
    check (the zero-overhead-when-unset claim, asserted in tests)."""
    san = _ACTIVE
    if san is None or not san._installed:
        return None
    return san.register_shared(name)


def maybe_install_from_env() -> LockSanitizer | None:
    """The ``raphtory_tpu/__init__`` hook: one env read when disabled.
    Arms BOTH sanitizers — the lock sanitizer and the mesh-divergence
    sanitizer share the one ``RTPU_SANITIZE`` switch."""
    if os.environ.get("RTPU_SANITIZE", "0") in ("", "0", "false"):
        return None
    mesh_install()
    return install()


# =====================================================================
# mesh-divergence sanitizer — the runtime half of rtpulint RT012
# =====================================================================


class MeshSanitizer:
    """Per-process mesh-dispatch fingerprint ring + barrier watchdog.

    The static RT012 rule catches collectives REACHABLE under
    per-process control flow; this class catches the ones that actually
    diverge in production. Each dispatch site calls
    :meth:`note_dispatch` BEFORE issuing the collective, appending a
    fingerprint record ``{seq, site, route, shape, dtype}`` to a
    bounded ring (``deque(maxlen=...)`` — old supersteps fall off, a
    long-running worker never grows) and journaling it as a ``mesh``
    record when the journal is on. ``seq`` is a per-process dispatch
    counter: in a correct SPMD program every process's sequence of
    fingerprints is IDENTICAL, so the first ``seq`` where two
    processes' fingerprints disagree is the first divergent superstep
    (:func:`mesh_prefix_divergence` does that comparison for
    ``/clusterz`` and the postmortem CLI).

    :meth:`barrier_watch` arms a one-shot watchdog around a barrier
    wait: if the collective has not returned after ``barrier_s``
    seconds (``RTPU_SANITIZE_BARRIER_S``, 0/unset = off), a
    ``mesh-barrier-stall`` finding and flight-recorder instant are
    emitted FROM THE TIMER THREAD — the symptom of divergence is one
    process blocked forever in a collective its peer never issued, so
    the report cannot wait for the call to return. The timer factory is
    injectable so tests drive the watchdog with a fake clock instead of
    sleeping.
    """

    def __init__(self, capacity: int = 256, barrier_s: float | None = None,
                 tracer=None, timer_factory=None):
        import collections

        # raw factory for the same reason as LockSanitizer: the mesh
        # sanitizer only ever runs alongside the lock sanitizer, and a
        # tracked internal mutex would show up in its own findings
        self._mu = _RAW_LOCK()
        self._ring = collections.deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._findings: list[dict] = []
        if barrier_s is None:
            raw = os.environ.get("RTPU_SANITIZE_BARRIER_S", "") or "0"
            try:
                barrier_s = float(raw)
            except ValueError:
                barrier_s = 0.0
        self.barrier_s = max(0.0, float(barrier_s))
        self._tracer = tracer
        self._timer_factory = timer_factory or threading.Timer
        self._journal = None   # resolved lazily; False = unavailable

    # ---- dispatch fingerprints ----

    def note_dispatch(self, site: str, route: str, shape_sig: str,
                      dtype: str) -> int:
        """Record one mesh dispatch; returns its sequence number."""
        with self._mu:
            seq = self._seq
            self._seq += 1
            rec = {"seq": seq, "site": str(site), "route": str(route),
                   "shape": str(shape_sig), "dtype": str(dtype)}
            self._ring.append(rec)
        # journaled OUTSIDE the collective: a dispatch that hangs (the
        # exact failure this exists for) still leaves its record behind
        self._journal_emit({"event": "dispatch", **rec})
        return seq

    def ring(self) -> list[dict]:
        """Snapshot of the retained fingerprint records, oldest first."""
        with self._mu:
            return [dict(r) for r in self._ring]

    def status_block(self) -> dict:
        """The ``/statusz`` block: counters plus the ring itself (the
        ring is what ``/clusterz`` cross-checks across processes)."""
        with self._mu:
            return {
                "dispatches": self._seq,
                "ring_capacity": self._ring.maxlen,
                "barrier_watchdog_s": self.barrier_s,
                "findings": len(self._findings),
                "ring": [dict(r) for r in self._ring],
            }

    # ---- barrier watchdog ----

    def barrier_watch(self, site: str, route: str):
        """Arm a one-shot stall watchdog for the barrier wait the caller
        is about to enter; returns a timer with ``.cancel()`` (call it
        when the wait returns) or None when the watchdog is off."""
        if self.barrier_s <= 0:
            return None
        san = self

        def _fire():
            finding = {
                "kind": "mesh-barrier-stall",
                "site": site,
                "route": route,
                "seconds": san.barrier_s,
                "dispatches": san._seq,
                "thread": threading.current_thread().name,
            }
            san._emit(
                finding,
                "barrier wait at %s (route %r) exceeded "
                "RTPU_SANITIZE_BARRIER_S=%.3gs — probable SPMD divergence; "
                "cross-check /clusterz mesh fingerprints for the first "
                "divergent superstep", site, route, san.barrier_s)

        t = self._timer_factory(self.barrier_s, _fire)
        if hasattr(t, "daemon"):
            t.daemon = True   # a hung barrier must not block interpreter exit
        t.start()
        return t

    # ---- reporting (LockSanitizer._emit shape, minus stacks) ----

    def _emit(self, finding: dict, msg: str, *fmt) -> None:
        with self._mu:
            self._findings.append(finding)
        _log.warning("sanitizer: " + msg, *fmt)
        tracer = self._tracer
        if tracer is None:
            try:
                from ..obs.trace import TRACER as tracer
            except Exception:
                tracer = False
            self._tracer = tracer
        if tracer:
            tracer.instant("sanitizer." + finding["kind"],
                           **{k: v for k, v in finding.items()
                              if k != "kind"})
        self._journal_emit({"event": finding["kind"],
                            **{k: v for k, v in finding.items()
                               if k != "kind"}})

    def _journal_emit(self, data: dict) -> None:
        j = self._journal
        if j is None:
            try:
                from ..obs import journal as j
            except Exception:
                j = False
            self._journal = j
        if j:
            j.emit("mesh", data)

    def findings(self, kind: str | None = None) -> list[dict]:
        with self._mu:
            out = list(self._findings)
        if kind:
            out = [f for f in out if f["kind"] == kind]
        return out

    def clear(self) -> None:
        with self._mu:
            self._findings.clear()
            self._ring.clear()
            self._seq = 0


def mesh_prefix_divergence(rings: dict) -> dict | None:
    """Cross-process fingerprint prefix check — the detector behind
    ``/clusterz`` and ``rtpu-postmortem reconstruct``.

    ``rings`` maps process id → list of fingerprint records (dicts with
    ``seq``/``site``/``route``/``shape``/``dtype`` keys, exactly what
    ``status_block()["ring"]`` or the journal's ``mesh`` dispatch
    records carry). Every process is compared against the lowest
    process id over the sequence numbers BOTH retain (rings are
    bounded, so only the overlapping window is comparable). Returns
    None when every common fingerprint agrees, else the FIRST divergent
    step::

        {"seq": ..., "process_a": ..., "fingerprint_a": ...,
         "process_b": ..., "fingerprint_b": ...}

    A process merely BEHIND its peers (fewer dispatches, all common
    ones agreeing) is not divergence — it is an in-flight straggler, a
    different signal, surfaced via the per-process dispatch counters.
    """
    def fp(rec: dict) -> str:
        return "|".join(str(rec.get(k, ""))
                        for k in ("site", "route", "shape", "dtype"))

    procs = sorted(rings)
    if len(procs) < 2:
        return None
    ref_p = procs[0]
    ref = {int(r["seq"]): r for r in rings[ref_p] if "seq" in r}
    for p in procs[1:]:
        cur = {int(r["seq"]): r for r in rings[p] if "seq" in r}
        for s in sorted(set(ref) & set(cur)):
            a, b = fp(ref[s]), fp(cur[s])
            if a != b:
                return {"seq": s, "process_a": ref_p, "fingerprint_a": a,
                        "process_b": p, "fingerprint_b": b}
    return None


#: the process-wide mesh sanitizer, set by mesh_install()
_MESH: MeshSanitizer | None = None


def mesh_install(**kwargs) -> MeshSanitizer:
    """Install (or return) the process-wide mesh sanitizer."""
    global _MESH
    if _MESH is None:
        _MESH = MeshSanitizer(**kwargs)
    return _MESH


def mesh_uninstall() -> None:
    global _MESH
    _MESH = None


def mesh_active() -> MeshSanitizer | None:
    return _MESH


def note_mesh_dispatch(site: str, route: str, shape_sig: str,
                       dtype: str) -> None:
    """One-line dispatch hook for the parallel engines: a single
    module-global falsy check when the mesh sanitizer is not installed
    (the zero-overhead-when-unset contract, same as note_shared)."""
    san = _MESH
    if san is not None:
        san.note_dispatch(site, route, shape_sig, dtype)


def mesh_barrier_watch(site: str, route: str):
    """Arm the barrier-stall watchdog, or None when disarmed — callers
    hold the handle and ``.cancel()`` it when the wait returns."""
    san = _MESH
    if san is None:
        return None
    return san.barrier_watch(site, route)
