"""Runtime lock sanitizer — deadlock-order and device-boundary findings.

``RTPU_SANITIZE=1`` (checked once, in ``raphtory_tpu/__init__``) wraps the
``threading.Lock`` / ``threading.RLock`` factories so every lock created
afterwards is tracked:

* **lock-order-cycle** — each acquisition with other locks held adds
  held→acquired edges to a process-wide lock-ordering graph; the first
  edge that closes a cycle (A taken under B somewhere, B taken under A
  elsewhere) is a potential deadlock and is reported ONCE per edge with
  both creation sites and both acquisition stacks.
* **lock-across-device-boundary** — ``jax.device_put`` / compiled-program
  dispatch can block for seconds on a busy or flapping interconnect;
  holding any sanitized lock across that boundary stalls every thread
  queued on it (the ingest writer blocking REST reads is the motivating
  shape). The sanitizer patches ``jax.device_put`` when jax is importable
  and reports a held-lock set at each crossing.

Findings go three ways: a ``logging`` warning, an in-process list
(``findings()``, what tests assert on), and an ``obs.trace`` instant so
the flight recorder timeline shows the hazard between the spans that
caused it.

Zero overhead when disabled: nothing is imported or patched unless
``install()`` runs, and ``threading.Lock`` stays the pristine C factory.
"""

from __future__ import annotations

import logging
import os
import threading
import traceback

_log = logging.getLogger("raphtory_tpu.analysis.sanitizer")

#: pristine factories, captured at import so install/uninstall can swap
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock


def _creation_site() -> str:
    """file:line of the frame that called Lock()/RLock(), skipping this
    module's own frames."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if not frame.filename.endswith("sanitizer.py"):
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "<unknown>"


class _TrackedLock:
    """Proxy over a raw lock that reports acquisition order to the
    sanitizer. Supports the full Lock/RLock surface the codebase uses,
    including being wrapped by ``threading.Condition``."""

    def __init__(self, san: "LockSanitizer", raw, reentrant: bool):
        self._san = san
        self._raw = raw
        self._reentrant = reentrant
        self.site = _creation_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # try-locks with fallback are a legitimate cycle-avoidance
            # idiom — only blocking acquires add ordering edges
            self._san._before_acquire(self)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._san._note_acquired(self)
        return got

    def release(self):
        self._san._note_released(self)
        return self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # threading.Condition(lock) probes _release_save/_acquire_restore/
        # _is_owned with try/except AttributeError to distinguish RLock
        # from Lock — delegation must preserve that (raising here when the
        # RAW lock lacks the attr), while keeping the held-stack honest
        # when Condition.wait releases/reacquires around the sleep
        raw_attr = getattr(self._raw, name)   # AttributeError propagates
        if name == "_release_save":
            def _release_save():
                self._san._note_released(self)
                return raw_attr()
            return _release_save
        if name == "_acquire_restore":
            def _acquire_restore(state):
                raw_attr(state)
                self._san._note_acquired(self)
            return _acquire_restore
        return raw_attr

    def __repr__(self):
        return f"<TrackedLock {self.site} over {self._raw!r}>"


class LockSanitizer:
    """Lock-ordering graph + device-boundary watcher.

    One instance is installed process-wide via :func:`install`; tests build
    private instances and call :meth:`install`/:meth:`uninstall` directly.
    """

    def __init__(self, tracer=None):
        # bookkeeping must use the RAW factory: a tracked internal lock
        # would recurse into its own sanitizer
        self._mu = _RAW_LOCK()
        self._local = threading.local()
        #: site → set of sites acquired while this one was held
        self._edges: dict[str, set] = {}
        #: (from, to) edges already reported (report each hazard once)
        self._reported: set = set()
        self._findings: list[dict] = []
        self._installed = False
        self._jax_patched = False
        self._tracer = tracer

    # ---- install / uninstall ----

    def install(self, patch_jax: bool = True) -> "LockSanitizer":
        """Swap the ``threading`` factories for tracking wrappers. Locks
        created BEFORE install stay untracked (import early)."""
        if self._installed:
            return self
        self._installed = True
        san = self

        def make_lock():
            return _TrackedLock(san, _RAW_LOCK(), reentrant=False)

        def make_rlock():
            return _TrackedLock(san, _RAW_RLOCK(), reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        if patch_jax:
            self._patch_jax()
        _log.info("lock sanitizer installed (RTPU_SANITIZE)")
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = _RAW_LOCK
        threading.RLock = _RAW_RLOCK
        self._unpatch_jax()
        self._installed = False

    def _patch_jax(self) -> None:
        try:
            import jax
        except Exception:
            return   # stripped environment: lock-order checking still works
        san = self
        raw_put = jax.device_put

        def checked_device_put(*args, **kwargs):
            san.check_boundary("device_put")
            return raw_put(*args, **kwargs)

        self._raw_device_put = raw_put
        jax.device_put = checked_device_put
        self._jax_patched = True

    def _unpatch_jax(self) -> None:
        if self._jax_patched:
            import jax

            jax.device_put = self._raw_device_put
            self._jax_patched = False

    # ---- per-thread held stack ----

    def _held(self) -> list:
        st = getattr(self._local, "held", None)
        if st is None:
            st = self._local.held = []
        return st

    # ---- acquisition hooks ----

    def _before_acquire(self, lock: _TrackedLock) -> None:
        held = self._held()
        if not held:
            return
        if lock._reentrant and any(h is lock for h in held):
            return   # RLock re-entry adds no ordering constraint
        for h in held:
            if h is lock:
                continue
            self._add_edge(h, lock)

    def _note_acquired(self, lock: _TrackedLock) -> None:
        self._held().append(lock)

    def _note_released(self, lock: _TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # ---- ordering graph ----

    def _add_edge(self, frm: _TrackedLock, to: _TrackedLock) -> None:
        a, b = frm.site, to.site
        if a == b:
            return   # two locks from one construction site (e.g. a pool)
        with self._mu:
            fresh = b not in self._edges.get(a, ())
            if fresh:
                self._edges.setdefault(a, set()).add(b)
            cycle = self._find_path(b, a) if fresh else None
        if cycle:
            # path is b→…→a; the new a→b edge closes it — report each
            # participating site once
            self._report_cycle([a] + cycle[:-1])

    def _find_path(self, start: str, goal: str):
        """DFS path start→…→goal in the edge graph (caller holds _mu),
        or None. A found path plus the new goal→start edge is a cycle."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_cycle(self, sites: list[str]) -> None:
        key = ("cycle", frozenset(sites))
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
        finding = {
            "kind": "lock-order-cycle",
            "sites": sites,
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=12)[:-3]),
        }
        self._emit(finding,
                   "potential deadlock: lock-order cycle %s",
                   " -> ".join(sites + [sites[0]]))

    # ---- device boundary ----

    def check_boundary(self, boundary: str) -> None:
        """Report any sanitized locks the calling thread holds while
        crossing ``boundary`` (device_put, compile, dispatch…). Public so
        engine code can mark additional boundaries explicitly."""
        held = [h.site for h in self._held()]
        if not held:
            return
        key = (boundary, tuple(held))
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
        finding = {
            "kind": "lock-across-device-boundary",
            "boundary": boundary,
            "held": held,
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=12)[:-3]),
        }
        self._emit(finding,
                   "lock(s) %s held across %s — a slow interconnect stalls "
                   "every thread queued on them", held, boundary)

    # ---- reporting ----

    def _emit(self, finding: dict, msg: str, *fmt) -> None:
        with self._mu:
            self._findings.append(finding)
        _log.warning("sanitizer: " + msg, *fmt)
        tracer = self._tracer
        if tracer is None:
            try:
                from ..obs.trace import TRACER as tracer
            except Exception:
                tracer = False
            self._tracer = tracer
        if tracer:
            attrs = {k: v for k, v in finding.items() if k != "stack"}
            attrs["sites"] = ",".join(
                finding.get("sites") or finding.get("held") or [])
            tracer.instant("sanitizer." + finding["kind"], **attrs)

    def findings(self, kind: str | None = None) -> list[dict]:
        with self._mu:
            out = list(self._findings)
        if kind:
            out = [f for f in out if f["kind"] == kind]
        return out

    def clear(self) -> None:
        with self._mu:
            self._findings.clear()
            self._reported.clear()
            self._edges.clear()


#: the process-wide instance, set by install()
_ACTIVE: LockSanitizer | None = None


def install(patch_jax: bool = True) -> LockSanitizer:
    """Install (or return) the process-wide sanitizer."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = LockSanitizer()
    _ACTIVE.install(patch_jax=patch_jax)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.uninstall()
        _ACTIVE = None


def active() -> LockSanitizer | None:
    return _ACTIVE


def maybe_install_from_env() -> LockSanitizer | None:
    """The ``raphtory_tpu/__init__`` hook: one env read when disabled."""
    if os.environ.get("RTPU_SANITIZE", "0") in ("", "0", "false"):
        return None
    return install()
