"""The rtpulint static rules — one AST pass per hazard class.

Every rule encodes an invariant this codebase has already violated (or
nearly violated) as it grew; the motivating bug for each is documented in
``docs/STATIC_ANALYSIS.md``. Rules are deliberately *project-shaped*: they
know the repo's idioms (compiled-program factories are ``lru_cache``'d
module functions that close over their parameters and return
``jax.jit(inner)``; retries back off with ``time.sleep``; env knobs live in
the ``RTPU_*`` namespace) and trade generality for precision on exactly
those shapes.

stdlib-only on purpose: the CI lint job runs without jax installed.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .findings import Finding, parse_suppressions, suppressed

#: rule id → slug. Adding a rule: implement ``_check_<slug_with_underscores>``
#: below, register here, document in docs/STATIC_ANALYSIS.md.
RULES = {
    "RT001": "env-not-in-cache-key",
    "RT002": "broad-except-retry",
    "RT003": "host-sync-in-trace",
    "RT004": "use-after-donate",
    "RT005": "nondeterminism-in-trace",
    "RT006": "unguarded-module-state",
    "RT007": "undocumented-knob",
    "RT008": "unused-import",
    "RT009": "blocking-call-under-lock",
    "RT010": "shared-state-without-common-lock",
    "RT011": "unbounded-growth-on-request-path",
    "RT012": "collective-under-divergent-control-flow",
    "RT013": "unstable-compile-key",
    "RT014": "resident-buffer-escape",
    "RT015": "device-op-on-ingest-path",
}

_ENV_VAR_RE = re.compile(r"^RTPU_[A-Z0-9_]+$")

_CACHE_DECORATORS = {"lru_cache", "cache"}
_JIT_NAMES = {"jit"}
_MUTATOR_METHODS = {
    "append", "add", "update", "setdefault", "insert", "extend", "pop",
    "popleft", "appendleft", "remove", "discard", "clear", "__setitem__",
}
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "defaultdict", "deque", "OrderedDict", "Counter",
}
_NONDET_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "time.perf_counter_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow", "uuid.uuid4",
}
_NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")


# ---------------------------------------------------------------------------
# module model


def _set_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._rtpu_parent = node  # type: ignore[attr-defined]


def _parent(node: ast.AST):
    return getattr(node, "_rtpu_parent", None)


def _ancestors(node: ast.AST):
    cur = _parent(node)
    while cur is not None:
        yield cur
        cur = _parent(cur)


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` for a Name/Attribute chain, "" for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _qualname(node: ast.AST) -> str:
    names = []
    cur: ast.AST | None = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = _parent(cur)
    return ".".join(reversed(names))


@dataclass
class Module:
    """One parsed source file plus the derived tables the rules share."""

    path: str             # absolute
    relpath: str          # as reported in findings
    src: str
    tree: ast.AST = field(init=False)
    lines: list[str] = field(init=False)
    pragmas: dict = field(init=False)
    #: bare name → module-scope (top-level or method) FunctionDefs
    functions: dict = field(init=False)
    #: RTPU_* env-var reads: (var, node) — feeds the project-level RT007
    env_reads: list = field(init=False)

    def __post_init__(self):
        self.tree = ast.parse(self.src, filename=self.relpath)
        _set_parents(self.tree)
        self.lines = self.src.splitlines()
        self.pragmas = parse_suppressions(self.lines)
        self.functions = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
        self.env_reads = []
        for node in ast.walk(self.tree):
            var = _env_read_var(node)
            if var is not None and _ENV_VAR_RE.match(var or ""):
                self.env_reads.append((var, node))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule, name=RULES[rule], path=self.relpath, line=line,
            col=getattr(node, "col_offset", 0) + 1, message=message,
            symbol=_qualname(node), line_text=self.line_text(line))


# ---------------------------------------------------------------------------
# shared detectors


def _env_read_var(node: ast.AST):
    """Return the env-var name for an ``os.environ``/``os.getenv`` read
    (``""`` when the key is dynamic), or None when ``node`` is not one."""
    if isinstance(node, ast.Call):
        target = None
        if isinstance(node.func, ast.Attribute):
            base = _dotted(node.func.value)
            if node.func.attr == "get" and base.endswith("environ"):
                target = node.args[0] if node.args else None
            elif node.func.attr == "getenv" and base in ("os", ""):
                target = node.args[0] if node.args else None
            else:
                return None
        else:
            return None
        if isinstance(target, ast.Constant) and isinstance(target.value, str):
            return target.value
        return ""
    if isinstance(node, ast.Subscript):
        if _dotted(node.value).endswith("environ"):
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                return key.value
            return ""
    return None


def _is_cached_def(node) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(target)
        if name.split(".")[-1] in _CACHE_DECORATORS:
            return True
    return False


def _is_jit_call(call: ast.Call) -> bool:
    name = _dotted(call.func)
    return name.split(".")[-1] in _JIT_NAMES


def _jit_decorated(node) -> bool:
    for dec in node.decorator_list:
        if isinstance(dec, ast.Call):
            if _is_jit_call(dec):
                return True
            # @partial(jax.jit, ...)
            if (_dotted(dec.func).split(".")[-1] == "partial" and dec.args
                    and isinstance(dec.args[0], (ast.Name, ast.Attribute))
                    and _dotted(dec.args[0]).split(".")[-1] in _JIT_NAMES):
                return True
        elif _dotted(dec).split(".")[-1] in _JIT_NAMES:
            return True
    return False


def _enclosing_def(node: ast.AST):
    return next((a for a in _ancestors(node)
                 if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))),
                None)


def _traced_defs(mod: Module) -> list:
    """Function defs that become traced/compiled code: ``@jit``-decorated,
    or passed by name as the first argument of a ``jax.jit(...)`` call.
    Name lookup is scoped: ``jax.jit(run)`` inside a factory resolves to
    the ``run`` defined in THAT factory, never a same-named method
    elsewhere in the module."""
    traced = []
    seen = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node) and id(node) not in seen:
                traced.append(node)
                seen.add(id(node))
        elif isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name):
                scope = _enclosing_def(node)
                for fn in mod.functions.get(arg0.id, []):
                    if _enclosing_def(fn) is scope and id(fn) not in seen:
                        traced.append(fn)
                        seen.add(id(fn))
    return traced


def _calls_sleep(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name.split(".")[-1] == "sleep":
                return True
    return False


# ---------------------------------------------------------------------------
# RT001 env-not-in-cache-key lives in concurrency.py now: the walk is the
# project-wide interprocedural one (module helpers AND cross-module
# helpers), run by both analyze_module and analyze_project.
# ---------------------------------------------------------------------------
# RT002 broad-except-retry


def _check_broad_except_retry(mod: Module) -> list[Finding]:
    """``except Exception`` inside a sleep/backoff loop whose handler never
    re-raises: programming errors (bad shapes, real OOM) burn the full
    backoff schedule (~70 s at the transfer defaults) before surfacing.
    Classified handlers — ones that conditionally ``raise`` non-transient
    errors, transfer-style — pass."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        types = []
        if node.type is None:
            types = [""]
        elif isinstance(node.type, ast.Tuple):
            types = [_dotted(e) for e in node.type.elts]
        else:
            types = [_dotted(node.type)]
        if not any(t in ("", "Exception", "BaseException") for t in types):
            continue
        # a handler that raises (even conditionally), breaks, or returns is
        # classifying or bailing out — not blindly retrying
        if any(isinstance(n, (ast.Raise, ast.Break, ast.Return))
               for body in node.body for n in ast.walk(body)):
            continue
        loop = next((a for a in _ancestors(node)
                     if isinstance(a, (ast.For, ast.While))), None)
        if loop is None or not _calls_sleep(loop):
            continue
        out.append(mod.finding(
            "RT002", node,
            "broad except inside a sleep/backoff loop hides programming "
            "errors behind the full retry schedule — use "
            "resilience/policy.RetryPolicy.run (classified, jittered, "
            "deadline-aware) or classify with "
            "resilience.policy.default_classify and re-raise non-transient"))
    return out


# ---------------------------------------------------------------------------
# RT003 host-sync-in-trace / RT005 nondeterminism-in-trace


def _check_host_sync_in_trace(mod: Module) -> list[Finding]:
    """Host-sync primitives inside traced function bodies: under ``jit``
    these either fail at trace time or (worse) silently constant-fold a
    tracer-dependent value at compile time."""
    out = []
    for fn in _traced_defs(mod):
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = None
            if isinstance(node.func, ast.Attribute):
                base = _dotted(node.func.value)
                if node.func.attr in ("item", "block_until_ready") and \
                        not base.startswith(("np", "numpy")):
                    msg = (f".{node.func.attr}() forces a device→host sync")
                elif node.func.attr in ("asarray", "array") and \
                        base in ("np", "numpy"):
                    msg = (f"{base}.{node.func.attr}() materialises a tracer "
                           f"on the host")
                elif node.func.attr == "device_get":
                    msg = "device_get() forces a device→host sync"
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in params:
                msg = (f"{node.func.id}() on traced argument "
                       f"{node.args[0].id!r} concretises a tracer")
            if msg:
                out.append(mod.finding(
                    "RT003", node,
                    f"{msg} inside jit-traced {fn.name!r} — hoist it out of "
                    f"the traced body"))
    return out


def _check_nondeterminism_in_trace(mod: Module) -> list[Finding]:
    """Wall-clock / unkeyed randomness inside traced bodies: the value is
    frozen at trace time and silently replayed by every cached execution."""
    out = []
    for fn in _traced_defs(mod):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name in _NONDET_CALLS or name.startswith(_NONDET_PREFIXES):
                out.append(mod.finding(
                    "RT005", node,
                    f"{name}() inside jit-traced {fn.name!r} is evaluated "
                    f"once at trace time and baked into the compiled "
                    f"program — thread the value in as an argument (or use "
                    f"keyed jax.random)"))
    return out


# ---------------------------------------------------------------------------
# RT004 use-after-donate


def _donating_factories(mod: Module) -> dict:
    """name → donated positional indices, for module functions that return
    ``jax.jit(..., donate_argnums=...)`` — the repo's compiled-factory
    idiom. The jit call may be WRAPPED in another call (the ledger's
    ``instrument(name, jax.jit(..., donate_argnums=...))`` idiom): the
    wrapper dispatches through to the jitted callable, so donation
    semantics — and this rule — must see through it."""
    out = {}
    for name, fns in mod.functions.items():
        for fn in fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Return) or \
                        not isinstance(node.value, ast.Call):
                    continue
                jit_call = None
                if _is_jit_call(node.value):
                    jit_call = node.value
                else:   # wrapper(... jax.jit(...) ...): unwrap one level
                    for arg in node.value.args:
                        if isinstance(arg, ast.Call) and _is_jit_call(arg):
                            jit_call = arg
                            break
                if jit_call is None:
                    continue
                pos = _donated_positions(jit_call)
                if pos:
                    out[name] = pos
    return out


def _donated_positions(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                pos = {e.value for e in v.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)}
                if pos:
                    return pos
        elif kw.arg == "donate_argnames":
            return set()   # names unsupported statically — still donating
    return None


def _donor_bindings(fn, factories, resolve=None) -> dict[str, set]:
    """Donating callables bound inside ``fn``:
    ``f = jax.jit(..., donate_argnums=…)`` | ``f = _compiled_apply(…)``.
    ``resolve(call)`` (optional) maps a call to donated positions through
    project-level resolution — the cross-module factory case."""
    donors: dict[str, set] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            call = node.value
            pos = None
            if _is_jit_call(call):
                pos = _donated_positions(call)
            else:
                callee = _dotted(call.func).split(".")[-1]
                pos = factories.get(callee)
                if pos is None and resolve is not None:
                    pos = resolve(call)
            if pos:
                donors[node.targets[0].id] = pos
    return donors


def _donate_flow(mod: Module, fn, donors: dict[str, set]) -> list[Finding]:
    """The read-after-donate dataflow over one function body, shared by
    the per-module rule and the project-level (cross-module factory)
    variant in concurrency.py."""
    out: list[Finding] = []
    if not donors:
        return out
    # name → sorted store linenos, for the staleness check
    stores: dict[str, list[int]] = {}
    loads: dict[str, list[ast.Name]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                stores.setdefault(node.id, []).append(node.lineno)
            else:
                loads.setdefault(node.id, []).append(node)
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in donors):
            continue
        for idx in sorted(donors[node.func.id]):
            if idx >= len(node.args):
                continue
            arg = node.args[idx]
            if not isinstance(arg, ast.Name):
                continue   # *starred / attribute args: can't track
            for use in loads.get(arg.id, []):
                if use.lineno <= node.lineno or use is arg:
                    continue
                # a store on the call line itself is the
                # ``x = f(x, …)`` rebind idiom — fresh value
                if any(node.lineno <= s <= use.lineno
                       for s in stores.get(arg.id, [])):
                    continue   # rebound in between — fresh value
                out.append(mod.finding(
                    "RT004", use,
                    f"{arg.id!r} is read after being donated to "
                    f"{node.func.id!r} (arg {idx}) on line "
                    f"{node.lineno} — its buffer may already be "
                    f"reused; copy first or re-order"))
    return out


def _check_use_after_donate(mod: Module) -> list[Finding]:
    """Reading a variable after passing it at a donated position: XLA has
    already reused its buffer, so the read returns garbage (TPU) or raises
    a deleted-buffer error — either way, after an arbitrary delay.
    Module-local factories only; cross-module factories are resolved by
    the project-level variant (concurrency.py)."""
    out: list[Finding] = []
    factories = _donating_factories(mod)
    for fns in mod.functions.values():
        for fn in fns:
            out.extend(_donate_flow(mod, fn,
                                    _donor_bindings(fn, factories)))
    return out


# ---------------------------------------------------------------------------
# RT006 unguarded-module-state


def _module_mutables(mod: Module) -> set[str]:
    names = set()
    body = getattr(mod.tree, "body", [])
    for stmt in body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            mutable = _dotted(value.func).split(".")[-1] in _MUTABLE_FACTORIES
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _under_lock(node: ast.AST) -> bool:
    for anc in _ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                if "lock" in _dotted(expr).lower() or \
                        "cond" in _dotted(expr).lower() or \
                        "cv" in _dotted(expr).lower():
                    return True
    return False


def _check_unguarded_module_state(mod: Module) -> list[Finding]:
    """Module-level mutable containers mutated from function bodies with no
    lock held: the ingest writer, transfer workers, and REST threads all
    import the same modules, so an unguarded dict/list mutation is a data
    race waiting for load."""
    out = []
    mutables = _module_mutables(mod)
    if not mutables:
        return out
    for node in ast.walk(mod.tree):
        fn = next((a for a in _ancestors(node)
                   if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))),
                  None)
        if fn is None:
            continue   # import-time mutation is single-threaded
        name = None
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.attr in _MUTATOR_METHODS:
            name = node.func.value.id
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgt = node.targets[0] if isinstance(node, ast.Assign) \
                else node.target
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name):
                name = tgt.value.id
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in mutables:
                    name = t.value.id
        if name not in mutables:
            continue
        # locals shadow the module name
        local = any(isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Store)
                    for n in ast.walk(fn)) and not any(
            isinstance(n, ast.Global) and name in n.names
            for n in ast.walk(fn))
        if local:
            continue
        if _under_lock(node):
            continue
        out.append(mod.finding(
            "RT006", node,
            f"module-level mutable {name!r} mutated without a lock — "
            f"threaded callers race; guard with a module lock or make the "
            f"mutation import-time-only"))
    return out


# ---------------------------------------------------------------------------
# RT008 unused-import


def _check_unused_import(mod: Module) -> list[Finding]:
    """Imports never referenced: dead weight that still costs import time
    and misleads readers about the module's dependencies."""
    if os.path.basename(mod.relpath) == "__init__.py":
        return []   # re-export surface — unused-by-design
    bound = []   # (bound_name, node)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound.append((a.asname or a.name.split(".")[0], node))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound.append((a.asname or a.name, node))
    if not bound:
        return []
    used = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name) and not isinstance(node.ctx, ast.Store):
            used.add(node.id)
    # names exported via __all__ count as used
    for node in getattr(mod.tree, "body", []):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets):
            if isinstance(node.value, (ast.List, ast.Tuple)):
                used.update(e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
    out = []
    for name, node in bound:
        if name not in used and not name.startswith("_"):
            out.append(mod.finding(
                "RT008", node,
                f"{name!r} is imported but never used"))
    return out


# ---------------------------------------------------------------------------
# RT007 undocumented-knob (project-level: needs the docs file)


def check_undocumented_knobs(modules: list[Module], docs_text: str,
                             docs_name: str) -> list[Finding]:
    """Every ``RTPU_*`` env var read in code must appear in the operations
    knob table — an undocumented knob is a support incident in waiting."""
    out = []
    reported = set()
    for mod in modules:
        for var, node in mod.env_reads:
            if var in docs_text:
                continue
            if (mod.relpath, var) in reported:
                continue
            reported.add((mod.relpath, var))
            out.append(mod.finding(
                "RT007", node,
                f"env knob {var!r} is read here but not documented in "
                f"{docs_name} — add a row to the knob table"))
    return out


# ---------------------------------------------------------------------------
# drivers

#: per-module passes, keyed by the rule id they implement (the key is the
#: timing bucket — RT003/RT004 also have project-level halves that land
#: in the same bucket)
_MODULE_CHECKS = {
    "RT002": _check_broad_except_retry,
    "RT003": _check_host_sync_in_trace,
    "RT004": _check_use_after_donate,
    "RT005": _check_nondeterminism_in_trace,
    "RT006": _check_unguarded_module_state,
    "RT008": _check_unused_import,
}


def _project_checks():
    """Rule id → project-level pass. Imported lazily: concurrency.py and
    devicecontract.py import this module's helpers, so a top-level
    import would cycle."""
    from . import concurrency as cc
    from . import devicecontract as dc

    return {
        "RT001": cc.check_env_in_cache_key_project,
        "RT003": cc.check_host_sync_in_trace_project,
        "RT004": cc.check_use_after_donate_project,
        "RT009": cc.check_blocking_under_lock,
        "RT010": cc.check_shared_state_locksets,
        "RT011": cc.check_unbounded_growth,
        "RT012": dc.check_collective_divergence,
        "RT013": dc.check_unstable_compile_key,
        "RT014": dc.check_resident_escape,
        "RT015": dc.check_device_op_on_ingest_path,
    }


def _analyze_modules(modules: list[Module],
                     timings: dict | None = None) -> list[Finding]:
    """Per-module + project-level passes over already-parsed modules,
    suppressions applied. ``timings`` (optional) collects per-rule wall
    seconds — the CI budget evidence."""
    from time import perf_counter

    from .interproc import Project

    def timed(rule_id: str, fn, *args):
        t0 = perf_counter()
        try:
            return fn(*args)
        finally:
            if timings is not None:
                timings[rule_id] = timings.get(rule_id, 0.0) + \
                    (perf_counter() - t0)

    findings: list[Finding] = []
    by_path = {m.relpath: m.pragmas for m in modules}
    for mod in modules:
        for rule_id, check in _MODULE_CHECKS.items():
            findings.extend(f for f in timed(rule_id, check, mod)
                            if not suppressed(f, mod.pragmas))
    t0 = perf_counter()
    project = Project(modules)
    if timings is not None:
        timings["model"] = timings.get("model", 0.0) + \
            (perf_counter() - t0)
    for rule_id, check in _project_checks().items():
        findings.extend(
            f for f in timed(rule_id, check, project)
            if not suppressed(f, by_path.get(f.path, {})))
    return findings


def analyze_module(src: str, relpath: str = "<string>",
                   path: str = "") -> list[Finding]:
    """Every rule except the docs-dependent knob audit over one source
    text (a single-module project), suppressions applied."""
    mod = Module(path=path or relpath, relpath=relpath, src=src)
    return _analyze_modules([mod])


def analyze_project(files: list[tuple[str, str]],
                    docs_text: str = "",
                    docs_name: str = "docs/OPERATIONS.md",
                    rules: set[str] | None = None,
                    timings: dict | None = None) -> list[Finding]:
    """Run every rule over ``files`` ([(relpath, source)]), including the
    cross-file knob audit and the interprocedural passes. Unparseable
    files yield a single parse-error finding rather than aborting the
    run. ``timings`` (optional dict) is filled with per-rule wall seconds
    — what the CI job prints against its 30 s budget."""
    from time import perf_counter

    modules: list[Module] = []
    findings: list[Finding] = []
    for relpath, src in files:
        try:
            modules.append(Module(path=relpath, relpath=relpath, src=src))
        except SyntaxError as e:
            findings.append(Finding(
                rule="RT000", name="parse-error", path=relpath,
                line=e.lineno or 1, col=(e.offset or 0) + 1,
                message=f"could not parse: {e.msg}"))
    findings.extend(_analyze_modules(modules, timings=timings))
    t0 = perf_counter()
    knob_findings = check_undocumented_knobs(modules, docs_text, docs_name)
    if timings is not None:
        timings["RT007"] = timings.get("RT007", 0.0) + \
            (perf_counter() - t0)
    by_path = {m.relpath: m.pragmas for m in modules}
    findings.extend(f for f in knob_findings
                    if not suppressed(f, by_path.get(f.path, {})))
    if rules:
        # RT000 always survives filtering: a parse error is the only
        # signal a file was never analyzed at all
        findings = [f for f in findings
                    if f.rule in rules or f.name in rules
                    or f.rule == "RT000"]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
