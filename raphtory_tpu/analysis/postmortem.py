"""postmortem — replay durable telemetry journals into cluster answers.

The journal (obs/journal.py) gets telemetry to disk before a process
dies; this module is the other half: load one or many journal
directories, merge every process's segments into a single time-ordered
cluster timeline, and answer the questions an operator asks over a
corpse — what was process 1 doing when it died, which queries were in
flight, where did the time go, and what regressed between two runs.

Stdlib-only, like the rest of ``raphtory_tpu.analysis`` —
``tools/rtpu-postmortem`` loads it with zero runtime deps. The CRC
framing is NOT re-implemented here: ``obs/journal.py`` (itself
stdlib-only and standalone-importable) is loaded by file path, so the
reader and the writer can never drift apart.

Subcommands (``tools/rtpu-postmortem <cmd> --help``):

* ``status DIR...`` — segment inventory per process: bytes, record and
  kind counts, torn tails (the SIGKILL signature), sequence gaps (the
  on-disk evidence of queue-overflow drops).
* ``timeline DIR...`` — the merged cluster timeline, filterable by
  ``--kind``, ``--trace``, ``--tenant``, ``--process``, ``--since`` /
  ``--until`` (unix seconds); ``--format json`` for machines.
* ``reconstruct DIR... --process N`` — a dead member's final story from
  its journal alone: last record, its final trace's sweep timeline,
  last live-epoch state per subscription, last query ledgers, the tail
  of fault/breaker/degrade/sched/mesh events — plus, when ≥2 processes
  journaled ``mesh`` dispatch fingerprints, the SPMD-divergence
  cross-check (the first superstep where fingerprints disagree, with
  both processes' fingerprints side by side).
* ``export DIR... --format chrome|collapsed`` — Chrome-trace JSON
  (span timestamps re-based onto each record's wall clock, so processes
  align on one axis) or collapsed stacks (self-time-weighted parent
  chains) for flamegraph tooling.
* ``diff A B`` — phase/kernel regression attribution between two runs:
  per-algorithm per-phase medians from ledger records and per-span-name
  duration medians, judged against ``--threshold``.

Torn or corrupt segment tails are skipped and COUNTED, never fatal —
a postmortem tool that crashes on the damage it exists to read would
be useless precisely when needed.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import statistics
import sys

_JOURNAL_MOD = None


def journal_mod():
    """``raphtory_tpu/obs/journal.py`` loaded by file path (no package
    import — ``raphtory_tpu/__init__`` would pull jax)."""
    global _JOURNAL_MOD
    if _JOURNAL_MOD is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "obs", "journal.py")
        spec = importlib.util.spec_from_file_location("rtpu_journal", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _JOURNAL_MOD = mod
    return _JOURNAL_MOD


# ---------------------------------------------------------------- loading


def load_segments(directories) -> list[dict]:
    """Every journal segment under ``directories``, scanned: one dict
    per segment with its intact records under ``_records``. Unreadable
    files become ``error`` rows (a half-dead disk is data here)."""
    jm = journal_mod()
    segs: list[dict] = []
    for directory in directories:
        try:
            names = sorted(os.listdir(directory))
        except OSError as e:
            segs.append({"dir": directory, "error": str(e)})
            continue
        for name in names:
            parsed = jm.parse_segment_name(name)
            if parsed is None:
                continue
            pi, seq = parsed
            path = os.path.join(directory, name)
            row = {"dir": directory, "file": name,
                   "process": pi, "seq": seq}
            try:
                records, report = jm.scan_report(path)
            except OSError as e:
                row["error"] = str(e)
                segs.append(row)
                continue
            row.update(bytes=report["bytes"], records=len(records),
                       torn=report["torn"], reason=report["reason"],
                       _records=records)
            segs.append(row)
    segs.sort(key=lambda s: (s.get("process", -1), s.get("seq", -1)))
    return segs


def merge_records(segs, processes=None) -> list[dict]:
    """One time-ordered cluster timeline: every intact record of every
    (selected) process, sorted by wall clock (ties: process, then the
    per-process emit sequence — both monotone within a process)."""
    out: list[dict] = []
    for s in segs:
        if "error" in s:
            continue
        if processes is not None and s["process"] not in processes:
            continue
        out.extend(s["_records"])
    out.sort(key=lambda r: (r.get("w", 0.0), r.get("p", 0),
                            r.get("s", 0)))
    return out


def seq_gaps(records) -> list[dict]:
    """Gaps in ONE process's emit sequence — the on-disk evidence that
    records were dropped (queue overflow) or lost with an unflushed
    batch. The journal assigns sequence numbers even to drops for
    exactly this reason."""
    seqs = sorted(r["s"] for r in records if isinstance(r.get("s"), int))
    gaps = []
    for a, b in zip(seqs, seqs[1:]):
        if b > a + 1:
            gaps.append({"after_seq": a, "missing": b - a - 1})
    return gaps


# ----------------------------------------------------------------- status


def status(segs) -> dict:
    """Per-process inventory + damage report."""
    procs: dict[int, dict] = {}
    errors = [s for s in segs if "error" in s]
    for s in segs:
        if "error" in s:
            continue
        p = procs.setdefault(s["process"], {
            "segments": 0, "bytes": 0, "records": 0, "torn_segments": 0,
            "kinds": {}, "first_wall": None, "last_wall": None})
        p["segments"] += 1
        p["bytes"] += s["bytes"]
        p["records"] += len(s["_records"])
        if s["torn"]:
            p["torn_segments"] += 1
        for r in s["_records"]:
            k = r.get("k", "?")
            p["kinds"][k] = p["kinds"].get(k, 0) + 1
            w = r.get("w")
            if isinstance(w, (int, float)):
                if p["first_wall"] is None or w < p["first_wall"]:
                    p["first_wall"] = w
                if p["last_wall"] is None or w > p["last_wall"]:
                    p["last_wall"] = w
    for pi, p in procs.items():
        mine = [r for s in segs if s.get("process") == pi
                and "error" not in s for r in s["_records"]]
        p["seq_gaps"] = seq_gaps(mine)
        p["dropped_records"] = sum(g["missing"] for g in p["seq_gaps"])
    out = {"processes": {f"process_{pi}": p
                         for pi, p in sorted(procs.items())},
           "segments_total": sum(1 for s in segs if "error" not in s),
           "records_total": sum(p["records"] for p in procs.values()),
           "torn_segments_total": sum(p["torn_segments"]
                                      for p in procs.values())}
    if errors:
        out["unreadable"] = [{k: s[k] for k in ("dir", "file", "error")
                              if k in s} for s in errors]
    return out


# --------------------------------------------------------------- timeline


def _summary_of(rec: dict) -> str:
    d = rec.get("d") or {}
    if rec.get("k") in ("span", "instant"):
        name = d.get("name", "?")
        dur = d.get("dur")
        return (f"{name} ({dur / 1000.0:.3f} ms)"
                if isinstance(dur, (int, float)) else name)
    keys = ("decision", "algorithm", "mode", "event", "seq", "site",
            "route", "state", "reason", "rule", "source", "job_id",
            "query_id", "metric")
    bits = [f"{k}={d[k]}" for k in keys if d.get(k) not in (None, "")]
    return " ".join(bits) if bits else json.dumps(d)[:80]


def timeline(records, kind=None, trace=None, tenant=None,
             since=None, until=None, limit=None) -> list[dict]:
    out = []
    for r in records:
        if kind is not None and r.get("k") != kind:
            continue
        if trace is not None and r.get("t") != trace:
            continue
        if tenant is not None and r.get("n") != tenant:
            continue
        w = r.get("w", 0.0)
        if since is not None and w < since:
            continue
        if until is not None and w > until:
            continue
        out.append(r)
    if limit is not None and len(out) > limit:
        out = out[-limit:]           # the tail is where postmortems live
    return out


# ------------------------------------------------------------ reconstruct


def reconstruct(records, process: int, tail: int = 10) -> dict:
    """A dead member's final state, from its journal alone."""
    mine = [r for r in records if r.get("p") == process]
    out: dict = {"process": process, "records": len(mine)}
    if not mine:
        out["error"] = f"no records for process {process}"
        return out
    last = mine[-1]
    out["last_record"] = {"kind": last.get("k"), "wall": last.get("w"),
                          "seq": last.get("s"),
                          "summary": _summary_of(last)}
    out["seq_gaps"] = seq_gaps(mine)
    metas = [r for r in mine if r.get("k") == "meta"]
    if metas:
        out["meta"] = metas[-1]["d"]
    # the final sweep: the last trace this process touched, replayed as
    # an ordered timeline (spans journal at COMPLETION, so the last
    # records of a killed sweep are the phases that finished; the phase
    # that was mid-flight is the gap after the last span)
    traced = [r for r in mine
              if r.get("k") in ("span", "instant") and r.get("t")]
    if traced:
        final_trace = traced[-1]["t"]
        sweep = [r for r in traced if r["t"] == final_trace]
        out["final_trace"] = {
            "trace_id": final_trace,
            "events": [{"kind": r["k"], "wall": r.get("w"),
                        "name": (r.get("d") or {}).get("name"),
                        "dur_us": (r.get("d") or {}).get("dur")}
                       for r in sweep[-50:]],
        }
    # last live-epoch state per subscription — the survivor cross-check
    epochs: dict[str, dict] = {}
    for r in mine:
        if r.get("k") == "epoch":
            d = r.get("d") or {}
            jid = str(d.get("job_id", "?"))
            epochs[jid] = {"wall": r.get("w"), **d}
    if epochs:
        out["last_epoch_by_job"] = epochs
    ledgers = [r for r in mine if r.get("k") == "ledger"]
    if ledgers:
        out["last_ledgers"] = [
            {"wall": r.get("w"), "trace": r.get("t"),
             "algorithm": (r.get("d") or {}).get("algorithm"),
             "job_id": (r.get("d") or {}).get("job_id"),
             "status": (r.get("d") or {}).get("status")}
            for r in ledgers[-tail:]]
    for kind in ("fault", "breaker", "degrade", "sched", "fresh", "mesh"):
        rows = [r for r in mine if r.get("k") == kind]
        if rows:
            out[f"last_{kind}"] = [
                {"wall": r.get("w"), "summary": _summary_of(r)}
                for r in rows[-tail:]]
    div = mesh_divergence(records)
    if div is not None:
        out["mesh_divergence"] = div
    return out


def mesh_divergence(records) -> dict | None:
    """The journal-replay SPMD-divergence cross-check: group every
    ``mesh`` dispatch record by process and run the same fingerprint
    prefix comparison ``/clusterz`` does live
    (``analysis.sanitizer.mesh_prefix_divergence``) — after a hang was
    SIGKILLed, the journals are all that is left to name the first
    superstep where the processes' collective sequences disagreed.
    Returns None when fewer than two processes journaled dispatches or
    every common fingerprint agrees."""
    from .sanitizer import mesh_prefix_divergence

    rings: dict[int, list] = {}
    for r in records:
        if r.get("k") != "mesh":
            continue
        d = r.get("d") or {}
        if d.get("event") != "dispatch" or "seq" not in d:
            continue
        rings.setdefault(int(r.get("p", 0)), []).append(d)
    if len(rings) < 2:
        return None
    return mesh_prefix_divergence(rings)


# ---------------------------------------------------------------- exports


def chrome_trace(records) -> dict:
    """Chrome-trace JSON over the merged timeline. Ring-event
    timestamps are per-process perf_counter epochs — NOT comparable
    across processes — so every event is re-based onto its journal
    record's wall clock (spans journal at completion: start = wall −
    duration). ``pid`` is the cluster process_index, which is what a
    cross-process view wants on the axis."""
    events = []
    for r in records:
        k = r.get("k")
        d = r.get("d") or {}
        w = r.get("w")
        if k not in ("span", "instant") or not isinstance(w, (int, float)):
            continue
        if k == "span":
            dur = float(d.get("dur") or 0.0)
            events.append({"ph": "X", "name": d.get("name", "?"),
                           "ts": w * 1e6 - dur, "dur": dur,
                           "pid": r.get("p", 0), "tid": d.get("tid", 0),
                           "args": d.get("args", {})})
        else:
            events.append({"ph": "i", "s": "t",
                           "name": d.get("name", "?"), "ts": w * 1e6,
                           "pid": r.get("p", 0), "tid": d.get("tid", 0),
                           "args": d.get("args", {})})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def collapsed_stacks(records) -> dict[str, int]:
    """``stack self_time_us`` lines for flamegraph tooling. Stacks are
    parent chains over span ids (per process — span ids are process
    local); weights are SELF time so a parent's bar doesn't double-count
    its children."""
    spans = [r for r in records if r.get("k") == "span"
             and isinstance((r.get("d") or {}).get("sid"), int)]
    by_sid: dict[tuple, dict] = {}
    child_us: dict[tuple, float] = {}
    for r in spans:
        d = r["d"]
        by_sid[(r.get("p", 0), d["sid"])] = r
        pk = (r.get("p", 0), d.get("parent"))
        child_us[pk] = child_us.get(pk, 0.0) + float(d.get("dur") or 0.0)
    lines: dict[str, int] = {}
    for r in spans:
        d = r["d"]
        p = r.get("p", 0)
        self_us = max(0.0, float(d.get("dur") or 0.0)
                      - child_us.get((p, d["sid"]), 0.0))
        stack = [str(d.get("name", "?"))]
        seen = {d["sid"]}
        cur = d.get("parent")
        while cur and (p, cur) in by_sid and cur not in seen:
            seen.add(cur)
            parent = by_sid[(p, cur)]["d"]
            stack.append(str(parent.get("name", "?")))
            cur = parent.get("parent")
        stack.append(f"process_{p}")
        key = ";".join(reversed(stack))
        lines[key] = lines.get(key, 0) + int(round(self_us))
    return lines


# ------------------------------------------------------------------- diff


def _run_profile(records) -> dict:
    """Medians a run diffs on: per-algorithm per-phase seconds (from
    ledger records) and per-span-name durations."""
    phases: dict[str, list[float]] = {}
    spans: dict[str, list[float]] = {}
    for r in records:
        d = r.get("d") or {}
        if r.get("k") == "ledger":
            alg = str(d.get("algorithm") or "?")
            for ph, sec in (d.get("phase_seconds") or {}).items():
                if isinstance(sec, (int, float)):
                    phases.setdefault(f"{alg}/{ph}", []).append(float(sec))
        elif r.get("k") == "span":
            dur = d.get("dur")
            if isinstance(dur, (int, float)):
                spans.setdefault(str(d.get("name", "?")), []).append(
                    float(dur) / 1e6)
    return {
        "phase_seconds": {k: {"median": statistics.median(v), "n": len(v)}
                          for k, v in phases.items()},
        "span_seconds": {k: {"median": statistics.median(v), "n": len(v)}
                         for k, v in spans.items()},
    }


def diff(records_a, records_b, threshold: float = 0.25) -> dict:
    """Attribute regressions between two runs: every phase/span metric
    present in BOTH, with relative delta; ``regressed`` when run B's
    median exceeds run A's by more than ``threshold`` (relative)."""
    a, b = _run_profile(records_a), _run_profile(records_b)
    out = {"threshold": threshold, "metrics": {}, "regressions": []}
    for table in ("phase_seconds", "span_seconds"):
        for key in sorted(set(a[table]) & set(b[table])):
            ma, mb = a[table][key]["median"], b[table][key]["median"]
            delta = (mb - ma) / ma if ma > 0 else 0.0
            row = {"a_median": round(ma, 6), "b_median": round(mb, 6),
                   "delta_rel": round(delta, 4),
                   "n_a": a[table][key]["n"], "n_b": b[table][key]["n"],
                   "regressed": delta > threshold}
            out["metrics"][f"{table}:{key}"] = row
            if row["regressed"]:
                out["regressions"].append(f"{table}:{key}")
    out["ok"] = not out["regressions"]
    return out


# -------------------------------------------------------------------- CLI


def _parse_processes(spec: str | None):
    if spec is None:
        return None
    return {int(p) for p in spec.split(",") if p.strip()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtpu-postmortem",
        description="replay durable telemetry journals "
                    "(obs/journal.py segments) into cluster answers")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def add_common(p, dirs="+"):
        p.add_argument("journals", nargs=dirs,
                       help="journal director(ies) of one run")
        p.add_argument("--process", default=None,
                       help="restrict to process index(es), comma-sep")

    p = sub.add_parser("status", help="segment inventory + damage report")
    add_common(p)

    p = sub.add_parser("timeline", help="merged, filtered cluster timeline")
    add_common(p)
    p.add_argument("--kind", default=None)
    p.add_argument("--trace", default=None)
    p.add_argument("--tenant", default=None)
    p.add_argument("--since", type=float, default=None,
                   help="unix seconds lower bound")
    p.add_argument("--until", type=float, default=None,
                   help="unix seconds upper bound")
    p.add_argument("--limit", type=int, default=200,
                   help="keep the LAST n matches (0 = all)")
    p.add_argument("--format", choices=("text", "json"), default="text")

    p = sub.add_parser("reconstruct",
                       help="a dead member's final state from its journal")
    p.add_argument("journals", nargs="+")
    p.add_argument("--process", type=int, required=True)
    p.add_argument("--tail", type=int, default=10,
                   help="rows kept per per-kind tail")

    p = sub.add_parser("export", help="chrome trace / collapsed stacks")
    add_common(p)
    p.add_argument("--format", choices=("chrome", "collapsed"),
                   default="chrome")
    p.add_argument("--out", default=None, help="output file (default stdout)")

    p = sub.add_parser("diff", help="phase/span regression attribution "
                                    "between two runs")
    p.add_argument("run_a", help="journal dir of the baseline run")
    p.add_argument("run_b", help="journal dir of the candidate run")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="relative slowdown that counts as a regression")

    args = ap.parse_args(argv)

    if args.cmd == "diff":
        ra = merge_records(load_segments([args.run_a]))
        rb = merge_records(load_segments([args.run_b]))
        if not ra or not rb:
            print("rtpu-postmortem: empty run "
                  f"(a={len(ra)} b={len(rb)} records)", file=sys.stderr)
            return 2
        result = diff(ra, rb, threshold=args.threshold)
        json.dump(result, sys.stdout, indent=1)
        print()
        for key in result["regressions"]:
            m = result["metrics"][key]
            print(f"  REGRESSION {key}: {m['a_median']} -> "
                  f"{m['b_median']} (+{m['delta_rel'] * 100:.1f}%)",
                  file=sys.stderr)
        return 0 if result["ok"] else 1

    segs = load_segments(args.journals)
    if not any("error" not in s for s in segs):
        print("rtpu-postmortem: no readable journal segments under "
              f"{args.journals}", file=sys.stderr)
        return 2
    procs = (_parse_processes(getattr(args, "process", None))
             if args.cmd != "reconstruct" else None)

    if args.cmd == "status":
        json.dump(status(segs), sys.stdout, indent=1)
        print()
        return 0

    if args.cmd == "timeline":
        rows = timeline(merge_records(segs, procs), kind=args.kind,
                        trace=args.trace, tenant=args.tenant,
                        since=args.since, until=args.until,
                        limit=args.limit or None)
        if args.format == "json":
            json.dump(rows, sys.stdout, indent=1)
            print()
        else:
            for r in rows:
                print(f"{r.get('w', 0):.6f} p{r.get('p', '?')} "
                      f"{r.get('k', '?'):8s} {r.get('t') or '-':14s} "
                      f"{_summary_of(r)}")
        return 0

    if args.cmd == "reconstruct":
        out = reconstruct(merge_records(segs), args.process,
                          tail=args.tail)
        json.dump(out, sys.stdout, indent=1)
        print()
        return 0 if "error" not in out else 1

    if args.cmd == "export":
        records = merge_records(segs, procs)
        if args.format == "chrome":
            doc = chrome_trace(records)
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(doc, f)
            else:
                json.dump(doc, sys.stdout)
                print()
        else:
            lines = collapsed_stacks(records)
            text = "".join(f"{k} {v}\n" for k, v in sorted(lines.items()))
            if args.out:
                with open(args.out, "w") as f:
                    f.write(text)
            else:
                sys.stdout.write(text)
        return 0

    return 2


if __name__ == "__main__":
    sys.exit(main())
