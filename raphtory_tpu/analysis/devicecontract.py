"""Device-contract rules (RT012–RT015): the SPMD/compile/buffer/ingest
invariants the multi-process mesh push depends on.

The single worst failure mode past N=2 processes is the silent SPMD hang:
one process takes a branch the others don't and dispatches a different
collective sequence, so the mesh blocks forever with no error (the
reference's BSP layer assumes lock-step dispatch across all partition
managers). These rules encode the static half of that contract over the
:class:`~.interproc.Project` model; the runtime half — the mesh-divergence
fingerprint ring and barrier watchdog — lives in ``sanitizer.py``.

* **RT012 collective-under-divergent-control-flow** — a mesh dispatch
  reachable under a branch conditioned on per-process data
  (``process_index()``, measured timings, breaker/advisor state, env
  reads). Silenced only by ``# rtpulint: spmd-uniform — <why>`` with a
  NON-EMPTY justification: the pragma is an assertion, not a mute.
* **RT013 unstable-compile-key** — an ``lru_cache``'d compiled-program
  factory keyed on a float-fresh/unhashable/identity-keyed value, or
  whose traced body reads state the key does not carry (generalizes
  RT001 beyond env reads — the compile-storm / wrong-program-reuse
  class).
* **RT014 resident-buffer-escape** — a donated arg captured by a closure
  or stored into a container/attribute that outlives its dispatch
  (extends the RT004 ``_donate_flow`` core to pre-donate captures, the
  half RT004's read-after-donate dataflow cannot see).
* **RT015 device-op-on-ingest-path** — jax calls reachable from the
  pipeline-sink/watermark/freshness chains, which must stay
  numpy/stdlib (the ≤5% ingest-overhead budget depends on it).

Precision-first like every other pass: anything the resolver is not
confident about is skipped, because the baseline is kept empty and every
finding costs a source fix or a reviewed pragma.
"""

from __future__ import annotations

import ast

from .concurrency import _chain_str, _dedupe, _finding, _qualname_of
from .findings import Finding, parse_spmd_uniform
from .interproc import FuncInfo, Project
from .rules import (Module, _ancestors, _dotted, _enclosing_def,
                    _env_read_var, _is_cached_def, _is_jit_call,
                    _module_mutables, _traced_defs)

#: calls that ARE a mesh dispatch / collective: the jax collective
#: vocabulary plus the cross-host replication entry point. A call that
#: RESOLVES to a function containing one of these (transitively) counts
#: as a dispatch site too — that is how ``sharded.run`` / the sparse
#: route / any future collective is covered without naming it here.
_MESH_DISPATCH_TAILS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "pshuffle", "shard_map",
    "process_allgather",
}

#: wall-clock sources whose values differ per process — a branch on a
#: measured duration is the classic accidental divergence
_TIMING_CALLS = {
    "time.perf_counter", "perf_counter", "time.perf_counter_ns",
    "time.monotonic", "monotonic", "time.monotonic_ns", "time.time",
}

#: attribute-chain substrings that mark per-process runtime state
#: (breaker trips and advisor decisions are driven by local timings)
_STATE_MARKERS = ("breaker", "advisor")


# ---------------------------------------------------------------------------
# shared call resolution (RT012/RT013/RT014 each classify most call
# sites in the project — one memoised resolve keeps the three passes
# inside the CI lint budget instead of re-running the resolver 3x)


def _resolve_cached(project: Project, mod: Module, call: ast.Call):
    cache = project.__dict__.setdefault("_devicecontract_resolve", {})
    key = id(call)
    if key not in cache:
        cache[key] = project.resolve_call(mod, _enclosing_def(call), call)
    return cache[key]


# ---------------------------------------------------------------------------
# RT012 collective-under-divergent-control-flow


def _dispatch_call_graph(project: Project) -> dict:
    """function key → set of resolvable callee keys (each call resolved
    once; shared by the dispatch fixpoint and the site classification)."""
    calls: dict[tuple, set] = {}
    for fi in project.functions.values():
        callees = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                callee = _resolve_cached(project, fi.mod, node)
                if callee is not None and callee.key != fi.key:
                    callees.add(callee.key)
        calls[fi.key] = callees
    return calls


def _direct_dispatcher(fn_node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and _dotted(n.func).split(".")[-1] in _MESH_DISPATCH_TAILS
               for n in ast.walk(fn_node))


def _dispatching_keys(project: Project, calls: dict) -> set:
    """Fixpoint closure of "contains a mesh dispatch" over the resolved
    call graph: ``sweep.ShardedSweep.run`` dispatches because
    ``sharded.run`` does."""
    disp = {fi.key for fi in project.functions.values()
            if _direct_dispatcher(fi.node)}
    changed = True
    while changed:
        changed = False
        for key, callees in calls.items():
            if key not in disp and callees & disp:
                disp.add(key)
                changed = True
    return disp


def _taint_label(node: ast.AST, tainted: set[str]) -> str | None:
    """A short label when ``node`` (an expression) depends on per-process
    data, else None. Sources: ``process_index`` (call or attribute),
    wall-clock timing calls, env reads, breaker/advisor state, and local
    names already marked tainted."""
    for sub in ast.walk(node):
        var = _env_read_var(sub)
        if var is not None:
            return f"env read {var or '<dynamic>'!r}"
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d.split(".")[-1] == "process_index":
                return "process_index()"
            if d in _TIMING_CALLS:
                return f"{d}() timing"
        elif isinstance(sub, ast.Attribute):
            if sub.attr == "process_index":
                return f"{_dotted(sub) or '.process_index'}"
            low = _dotted(sub).lower()
            if any(m in low for m in _STATE_MARKERS):
                return f"{_dotted(sub)} state"
        elif isinstance(sub, ast.Name) and sub.id in tainted:
            return f"{sub.id!r} (per-process value)"
    return None


def _tainted_names(fn_node: ast.AST) -> set[str]:
    """Local names (in ``fn_node``'s whole subtree, closures included)
    assigned from per-process expressions, to a fixpoint so
    ``t0 = perf_counter(); dt = now - t0; slow = dt > x`` chains taint."""
    tainted: set[str] = set()
    for _ in range(4):
        before = len(tainted)
        for sub in ast.walk(fn_node):
            value = targets = None
            if isinstance(sub, ast.Assign):
                value, targets = sub.value, sub.targets
            elif isinstance(sub, ast.AugAssign):
                value, targets = sub.value, [sub.target]
            elif isinstance(sub, ast.NamedExpr):
                value, targets = sub.value, [sub.target]
            if value is None or _taint_label(value, tainted) is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
        if len(tainted) == before:
            break
    return tainted


def check_collective_divergence(project: Project) -> list[Finding]:
    """RT012: a mesh dispatch (collective call, or call into a function
    that transitively dispatches one) under a branch/loop conditioned on
    per-process data. If any process takes a different arm, the
    collective sequences diverge and the mesh blocks forever with no
    error. A genuinely uniform site is declared
    ``# rtpulint: spmd-uniform — <why>`` on the dispatch line or the
    branch line; the justification is enforced non-empty."""
    calls = _dispatch_call_graph(project)
    disp = _dispatching_keys(project, calls)
    out: list[Finding] = []
    spmd_by_mod = {m.relpath: parse_spmd_uniform(m.lines)
                   for m in project.modules}
    for fi in sorted(project.functions.values(),
                     key=lambda f: (f.mod.relpath, f.node.lineno)):
        mod = fi.mod
        spmd = spmd_by_mod[mod.relpath]
        sites: list[tuple] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            tail = _dotted(node.func).split(".")[-1]
            label = None
            if tail in _MESH_DISPATCH_TAILS:
                label = tail
            else:
                callee = _resolve_cached(project, mod, node)
                if callee is not None and callee.key in disp and \
                        callee.key != fi.key:
                    label = callee.label
            if label is not None:
                sites.append((node, label))
        if not sites:
            continue   # taint is computed only where a dispatch exists
        tainted = _tainted_names(fi.node)
        for node, label in sites:
            branch = why = None
            for anc in _ancestors(node):
                if anc is fi.node:
                    break
                test = None
                if isinstance(anc, (ast.If, ast.While, ast.IfExp)):
                    test = anc.test
                elif isinstance(anc, (ast.For, ast.AsyncFor)):
                    test = anc.iter
                if test is None or any(s is node for s in ast.walk(test)):
                    continue   # the dispatch IS the condition — it runs
                why = _taint_label(test, tainted)
                if why is not None:
                    branch = anc
                    break
            if branch is None:
                continue
            just = spmd.get(node.lineno)
            if just is None:
                just = spmd.get(branch.lineno)
            if just:
                continue   # reviewed uniformity assertion — honoured
            empty_pragma = (
                " (an spmd-uniform pragma is present but its "
                "justification is EMPTY — write why every process takes "
                "the same arm)") if just is not None else ""
            out.append(_finding(
                mod, "RT012", node,
                f"mesh dispatch {label!r} is reachable under a branch "
                f"conditioned on per-process data ({why}, line "
                f"{branch.lineno}) — if any process takes a different "
                f"arm the collective sequences diverge and the mesh "
                f"hangs; make the condition SPMD-uniform, hoist the "
                f"dispatch, or declare the site "
                f"`# rtpulint: spmd-uniform — <why>`{empty_pragma}",
                symbol=_qualname_of(mod, node)))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# RT013 unstable-compile-key


def _compile_factories(project: Project) -> list[FuncInfo]:
    """``lru_cache``'d functions that build compiled programs (contain a
    ``jax.jit``/``shard_map`` call) — the repo's compiled-factory idiom.
    Plain lru_caches of host data are out of scope: their keys cannot
    cause a compile storm."""
    out = []
    for fi in project.functions.values():
        if not _is_cached_def(fi.node):
            continue
        if any(isinstance(n, ast.Call)
               and (_is_jit_call(n)
                    or _dotted(n.func).split(".")[-1] == "shard_map")
               for n in ast.walk(fi.node)):
            out.append(fi)
    return out


def _factory_traced_defs(mod: Module, fi: FuncInfo) -> list:
    """Inner defs of ``fi`` that become compiled code: jit-decorated or
    jit-called (via ``_traced_defs``) plus defs passed by name into a
    ``shard_map``/``_shard_map`` call — the SPMD factory shape, where
    the shard_mapped fn is jitted as a value (``jax.jit(fn)``) and the
    name-based jit scan cannot see it."""
    inner = [t for t in _traced_defs(mod)
             if any(a is fi.node for a in _ancestors(t))]
    seen = {id(t) for t in inner}
    by_name: dict[str, list] = {}
    for n in ast.walk(fi.node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                n is not fi.node:
            by_name.setdefault(n.name, []).append(n)
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Call) and \
                _dotted(n.func).split(".")[-1] in ("shard_map",
                                                   "_shard_map"):
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(arg, ast.Name):
                    for d in by_name.get(arg.id, []):
                        if id(d) not in seen:
                            inner.append(d)
                            seen.add(id(d))
    return inner


def _fn_params(defnode) -> set[str]:
    a = defnode.args
    params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        params.add(a.vararg.arg)
    if a.kwarg:
        params.add(a.kwarg.arg)
    return params


def _unstable_arg_label(arg: ast.AST, timing_locals: set[str]) -> str | None:
    """Why ``arg`` destabilises an lru_cache key, or None."""
    if isinstance(arg, ast.Lambda):
        return ("a lambda is identity-keyed — every call builds a new "
                "key, the cache never hits, and each dispatch recompiles")
    if isinstance(arg, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                        ast.DictComp, ast.SetComp)):
        return "an unhashable container literal cannot be a cache key"
    if isinstance(arg, ast.Call) and _dotted(arg.func) in _TIMING_CALLS:
        return ("a measured timing is a fresh float every call — every "
                "dispatch makes a new key and recompiles (compile storm)")
    if isinstance(arg, ast.Name) and arg.id in timing_locals:
        return (f"{arg.id!r} holds a measured timing — a fresh float "
                f"every call; every dispatch makes a new key and "
                f"recompiles (compile storm)")
    return None


def _timing_locals(fn_node: ast.AST) -> set[str]:
    """Names assigned from wall-clock calls (or arithmetic over them)
    inside ``fn_node`` — candidate compile-storm key components."""
    tainted: set[str] = set()
    for _ in range(3):
        before = len(tainted)
        for sub in ast.walk(fn_node):
            if not isinstance(sub, ast.Assign):
                continue
            hit = any(
                (isinstance(s, ast.Call) and _dotted(s.func)
                 in _TIMING_CALLS)
                or (isinstance(s, ast.Name) and s.id in tainted)
                for s in ast.walk(sub.value))
            if hit:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
        if len(tainted) == before:
            break
    return tainted


def check_unstable_compile_key(project: Project) -> list[Finding]:
    """RT013: compiled-program factories with unstable or incomplete
    cache keys. Two halves: (a) the traced body reads module-level
    mutable state the key does not carry — the wrong-program-reuse bug
    (the value is baked in at trace time, then the stale program is
    replayed after the state changes); (b) a call site passes a key
    component that is fresh per call (timing float, lambda) or
    unhashable — the compile-storm bug. Generalizes RT001 beyond env
    reads."""
    out: list[Finding] = []
    factories = _compile_factories(project)
    factory_keys = {fi.key for fi in factories}

    # (a) traced bodies reading un-keyed module mutables
    for fi in sorted(factories, key=lambda f: (f.mod.relpath,
                                               f.node.lineno)):
        mod = fi.mod
        mutables = _module_mutables(mod)
        if not mutables:
            continue
        for inner in _factory_traced_defs(mod, fi):
            shadowed = _fn_params(inner) | {
                n.id for n in ast.walk(inner)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}
            for node in ast.walk(inner):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in mutables and node.id not in shadowed:
                    out.append(_finding(
                        mod, "RT013", node,
                        f"traced body {inner.name!r} reads module-level "
                        f"mutable {node.id!r}, which is baked in at "
                        f"trace time but is NOT part of lru_cache'd "
                        f"{fi.node.name!r}'s key — the cached program "
                        f"silently replays the stale value; thread it "
                        f"in as a factory argument",
                        symbol=_qualname_of(mod, node)))

    # (b) unstable key components at factory call sites
    if not factory_keys:
        return _dedupe(out)
    for fi in sorted(project.functions.values(),
                     key=lambda f: (f.mod.relpath, f.node.lineno)):
        mod = fi.mod
        timing = None   # computed lazily: most functions call no factory
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolve_cached(project, mod, node)
            if callee is None or callee.key not in factory_keys:
                continue
            if timing is None:
                timing = _timing_locals(fi.node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                why = _unstable_arg_label(arg, timing)
                if why is None:
                    continue
                out.append(_finding(
                    mod, "RT013", arg,
                    f"unstable cache-key component passed to compiled-"
                    f"program factory {callee.node.name!r}: {why}; pass "
                    f"a stable, hashable value (quantise timings, hoist "
                    f"callables to module scope)",
                    symbol=_qualname_of(mod, node)))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# RT014 resident-buffer-escape


_STORE_METHODS = {"append", "add", "insert", "appendleft", "setdefault",
                  "put", "put_nowait"}


def _donor_calls(fn_node: ast.AST, donors: dict[str, set]):
    """(call node, donated-arg Name) pairs inside ``fn_node``."""
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in donors):
            continue
        for idx in sorted(donors[node.func.id]):
            if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                yield node, node.args[idx]


def _name_free_in(defnode, name: str) -> bool:
    """True when ``name`` is read free (closure-captured) inside the
    nested def/lambda ``defnode``."""
    if name in _fn_params(defnode):
        return False
    body = defnode.body if isinstance(defnode, ast.Lambda) \
        else ast.Module(body=defnode.body, type_ignores=[])
    assigned = any(isinstance(n, ast.Name) and n.id == name
                   and isinstance(n.ctx, (ast.Store, ast.Del))
                   for n in ast.walk(body))
    if assigned:
        return False
    return any(isinstance(n, ast.Name) and n.id == name
               and isinstance(n.ctx, ast.Load)
               for n in ast.walk(body))


def check_resident_escape(project: Project) -> list[Finding]:
    """RT014: a donated buffer that outlives its dispatch. RT004's
    dataflow flags READS after the donating call; this rule flags the
    two escapes that happen textually BEFORE it — a closure capturing
    the donated name (late binding: the closure sees the donated buffer
    no matter where it was defined) and a container/attribute store of
    the name above the donating call (the stored reference — e.g. a
    ResidentRegistry-tracked or cached buffer — dangles once XLA reuses
    the pages)."""
    from .concurrency import donating_factories_project
    from .rules import _donating_factories, _donor_bindings

    proj_factories = donating_factories_project(project)
    out: list[Finding] = []
    local_factories = {m.relpath: _donating_factories(m)
                       for m in project.modules}
    for fi in sorted(project.functions.values(),
                     key=lambda f: (f.mod.relpath, f.node.lineno)):
        mod = fi.mod

        def resolve(call, _mod=mod):
            callee = _resolve_cached(project, _mod, call)
            if callee is None:
                return None
            return proj_factories.get((callee.mod.relpath,
                                       callee.node.name))

        donors = _donor_bindings(fi.node, local_factories[mod.relpath],
                                 resolve=resolve)
        if not donors:
            continue
        stores: dict[str, list[int]] = {}
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, (ast.Store, ast.Del)):
                stores.setdefault(n.id, []).append(n.lineno)

        for call, arg in _donor_calls(fi.node, donors):
            # (1) closure capture — flag unless the name is rebound
            # after the donate (then the closure's late-bound read sees
            # the fresh value, the x = f(x) idiom)
            rebound_after = any(s > call.lineno
                                for s in stores.get(arg.id, []))
            if not rebound_after:
                for sub in ast.walk(fi.node):
                    if not isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef,
                                            ast.Lambda)):
                        continue
                    if sub is fi.node or \
                            any(s is call for s in ast.walk(sub)):
                        continue   # the donate happens inside the closure
                    if _name_free_in(sub, arg.id):
                        cname = getattr(sub, "name", "<lambda>")
                        out.append(_finding(
                            mod, "RT014", call,
                            f"{arg.id!r} is donated to "
                            f"{call.func.id!r} but also captured by "
                            f"closure {cname!r} (line {sub.lineno}) — "
                            f"the closure outlives the dispatch and "
                            f"reads a buffer XLA has already reused; "
                            f"capture a copy or rebind after dispatch",
                            symbol=_qualname_of(mod, call)))
                        break
            # (2) container/attribute store above the donating call
            for n in ast.walk(fi.node):
                tgt = val = None
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                        isinstance(n.targets[0],
                                   (ast.Subscript, ast.Attribute)):
                    tgt, val = n.targets[0], n.value
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _STORE_METHODS:
                    if any(isinstance(a, ast.Name) and a.id == arg.id
                           for a in n.args):
                        tgt, val = n.func.value, ast.Name(
                            id=arg.id, ctx=ast.Load())
                if tgt is None or not (isinstance(val, ast.Name)
                                       and val.id == arg.id):
                    continue
                if not (n.lineno < call.lineno):
                    continue   # post-donate loads are RT004's half
                # a rebind between store and donate means the stored
                # reference is an OLDER object, not the donated one
                if any(n.lineno < s <= call.lineno
                       for s in stores.get(arg.id, [])):
                    continue
                # the slot being overwritten after the dispatch clears
                # the stale reference (self.state = fresh_result)
                tdot = _dotted(tgt if isinstance(tgt, ast.Attribute)
                               else getattr(tgt, "value", tgt))
                overwritten = any(
                    isinstance(m2, ast.Assign) and m2.lineno > call.lineno
                    and any(_dotted(t2 if isinstance(t2, ast.Attribute)
                                    else getattr(t2, "value", t2)) == tdot
                            and tdot for t2 in m2.targets)
                    for m2 in ast.walk(fi.node))
                if overwritten:
                    continue
                out.append(_finding(
                    mod, "RT014", call,
                    f"{arg.id!r} is stored into {tdot or 'a container'!r}"
                    f" (line {n.lineno}) and then donated to "
                    f"{call.func.id!r} — the stored reference outlives "
                    f"the dispatch and dangles once XLA reuses the "
                    f"buffer; store a copy or the dispatch result "
                    f"instead",
                    symbol=_qualname_of(mod, call)))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# RT015 device-op-on-ingest-path


#: relpath fragments that mark the ingest hot path: the pipeline sink,
#: the watermark registry, the result sink, and the freshness tracker.
#: Everything reachable from functions in these modules must stay
#: numpy/stdlib — the ≤5% ingest-overhead budget (docs/INGESTION.md)
#: assumes no device transfer, trace, or compile ever rides a batch.
_INGEST_PATH_MODULES = ("ingestion/pipeline", "ingestion/watermark",
                        "jobs/sink", "obs/freshness")

#: jax entry points that are pure host-side bookkeeping — safe anywhere
_INGEST_SAFE_JAX = {"jax.process_index", "jax.process_count",
                    "jax.devices", "jax.local_devices",
                    "jax.device_count", "jax.local_device_count"}


def check_device_op_on_ingest_path(project: Project) -> list[Finding]:
    """RT015: a jax/jnp call reachable from an ingest-chain function.
    The first device op on the ingest path pays device transfer + maybe
    a trace + maybe a compile — seconds, against a per-batch budget of
    microseconds — and it does so on the writer thread, stalling the
    watermark for every consumer."""
    out: list[Finding] = []
    reported: set = set()
    roots = [fi for fi in project.functions.values()
             if any(frag in fi.mod.relpath.replace("\\", "/")
                    for frag in _INGEST_PATH_MODULES)]

    for root in sorted(roots, key=lambda f: (f.mod.relpath,
                                             f.node.lineno)):
        def visit(fn: FuncInfo, node, locks, chain, _root=root):
            if not isinstance(node, ast.Call):
                return
            d = _dotted(node.func)
            base = d.split(".")[0]
            if base not in ("jax", "jnp") or d in _INGEST_SAFE_JAX:
                return
            if id(node) in reported:
                return
            reported.add(id(node))
            out.append(_finding(
                fn.mod, "RT015", node,
                f"device op {d}() is reachable from ingest-path "
                f"{_root.label!r} (path: {_chain_str(chain)}) — the "
                f"ingest hot path must stay numpy/stdlib (≤5% overhead "
                f"budget); move device work behind the job/engine "
                f"boundary",
                symbol=_qualname_of(fn.mod, node)))

        project.walk_from(root, visit, max_depth=4)
    return _dedupe(out)
