"""perfwatch — the perf-regression sentinel over the BENCH_* trajectory.

The repo has committed one benchmark artifact per round since PR 2, but
the history was write-only: nothing READ the JSON, so a regression only
surfaced if a human happened to diff numbers across rounds. perfwatch
closes the loop (stdlib-only, like the rest of ``raphtory_tpu.analysis``
— ``tools/perfwatch`` loads it with zero runtime deps):

1. **Collect** — every ``BENCH_*.json`` artifact is parsed tolerantly
   (the formats drifted across rounds: ``{row}``, ``{rows}``,
   ``{parsed}``, suite ``{rows}``, and raw bench JSONL output), keyed by
   the row's ``config`` (fallback: metric string), ordered by the round
   number in the filename.
2. **Fit** — per metric, a noise band around the history median. The
   band floor depends on the UNIT class, because the trajectory spans
   different machines (dev container, CI runners, the TPU rig):
   *ratio-like* metrics (``percent_*``, ``x_*`` speedups) are
   machine-portable and get tight bands; *absolute* metrics
   (``views/sec``, ``seconds``, ``updates/sec``) drift with the host and
   get wide bands. Spread widens the band further (median absolute
   deviation, scaled).
3. **Judge** — the head value (an explicit ``--head`` file, or the
   highest-round artifact when absent) regresses when it falls outside
   the band in the unit's "worse" direction. Exit 1 on any regression;
   ``--report`` writes the full judgement JSON for the CI artifact.

The ledger snapshot ``bench.py --config ledger_overhead`` embeds in its
row (``detail.ledger``) rides through the same machinery: its phase
seconds surface as extra watchable series once two rounds carry them.

``--selftest`` runs the built-in calibration (a synthetic 2x slowdown
must flag; a within-noise head must pass) — the cheap CI step that
proves the sentinel can actually fire before it is trusted to gate.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import statistics
import sys

#: per-unit-class (direction, relative-band floor). Direction is which
#: way "worse" points; the floor is the minimum relative deviation that
#: counts as a regression (wide for machine-dependent absolutes, tight
#: for portable ratios). ``percent`` units use an ABSOLUTE band in
#: percentage points instead (a 1% → 3% overhead move is +2pp, not 3x).
_UNIT_CLASSES = (
    # improvement-direction percents (percent_faster_*) must match BEFORE
    # the generic lower-is-better percent rule — a binned-kernel speedup
    # coming in ABOVE its trajectory is good news, not a regression
    ("percent_faster", ("higher", None)),
    ("percent", ("lower", None)),        # absolute band, see _PERCENT_PP
    ("x_", ("higher", 0.30)),
    ("views/sec", ("higher", 0.45)),
    ("updates/sec", ("higher", 0.45)),
    ("seconds", ("lower", 0.45)),
    ("error", (None, None)),             # never judged
)
_PERCENT_PP = 10.0    # percentage-point band floor for percent units
_MAD_SCALE = 4.0      # band widens by this many scaled MADs


def _unit_rule(unit: str):
    unit = (unit or "").lower()
    for prefix, rule in _UNIT_CLASSES:
        if unit.startswith(prefix) or prefix in unit:
            return rule
    return (None, None)


#: round assigned to artifacts with no rNN in the filename
#: (BENCH_SUITE_LATEST.json): "undated" artifacts are the newest run by
#: convention, so they sort after every numbered round
_ROUND_LATEST = 10**6


def _round_of(path: str) -> int:
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else _ROUND_LATEST


def _is_row(obj) -> bool:
    return (isinstance(obj, dict) and "value" in obj
            and ("metric" in obj or "config" in obj))


def journal_rows(directory: str) -> list[dict]:
    """Bench-shaped rows derived from a telemetry-journal directory
    (obs/journal.py segments): per-algorithm per-phase median seconds
    from the journaled query ledgers, plus per-span-name duration
    medians. A journal dir passed as trajectory or ``--head`` thereby
    rides the same band machinery as a committed BENCH artifact — the
    postmortem plane's evidence doubles as a perf series."""
    from . import postmortem

    profile = postmortem._run_profile(
        postmortem.merge_records(postmortem.load_segments([directory])))
    rows = []
    for prefix, table in (("journal_phase", "phase_seconds"),
                          ("journal_span", "span_seconds")):
        for key, st in sorted(profile[table].items()):
            rows.append({"config": f"{prefix}:{key}",
                         "value": st["median"], "unit": "seconds",
                         "detail": {"n": st["n"]}})
    return rows


def load_rows(path: str) -> list[dict]:
    """Bench rows from one artifact, across every format the repo has
    committed: ``{row}``, ``{rows}``, ``{parsed}``, a bare row, a list of
    rows, or bench.py's raw JSONL stdout. A DIRECTORY is read as a
    telemetry-journal dir (``journal_rows``)."""
    if os.path.isdir(path):
        return journal_rows(path)
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if _is_row(obj):
                rows.append(obj)
        return rows
    if isinstance(doc, list):
        return [r for r in doc if _is_row(r)]
    if not isinstance(doc, dict):
        return []
    if _is_row(doc):
        return [doc]
    out = []
    for key in ("row", "parsed"):
        if _is_row(doc.get(key)):
            out.append(doc[key])
    for r in doc.get("rows") or []:
        if _is_row(r):
            out.append(r)
    return out


def _key_of(row: dict) -> str:
    return str(row.get("config") or row.get("metric"))


def collect_series(paths) -> dict:
    """{metric_key: [(round, value, unit), ...]} over the artifacts,
    ascending by round (ties keep file order). Non-numeric and
    error-unit rows are dropped."""
    series: dict[str, list] = {}
    for path in sorted(paths, key=_round_of):
        rnd = _round_of(path)
        try:
            rows = load_rows(path)
        except OSError:
            continue
        for row in rows:
            val = row.get("value")
            unit = str(row.get("unit") or "")
            if not isinstance(val, (int, float)) or "error" in unit:
                continue
            series.setdefault(_key_of(row), []).append(
                (rnd, float(val), unit))
    return series


def judge(history: list[float], head: float, unit: str) -> dict:
    """One metric's verdict: fit the noise band over ``history`` and
    place ``head`` against it. Returns a judgement dict with
    ``regressed`` set; non-judgeable units / empty history report
    ``skipped`` with the reason."""
    direction, rel_floor = _unit_rule(unit)
    out = {"unit": unit, "head": head, "n_history": len(history),
           "regressed": False}
    if direction is None:
        out["skipped"] = f"unit {unit!r} not judged"
        return out
    if not history:
        out["skipped"] = "no history"
        return out
    base = statistics.median(history)
    mad = (statistics.median(abs(h - base) for h in history)
           if len(history) > 1 else 0.0)
    out["baseline_median"] = round(base, 6)
    out["history_mad"] = round(mad, 6)
    if "percent" in (unit or "").lower():
        band = max(_PERCENT_PP, _MAD_SCALE * mad)
        worse_by = (head - base) if direction == "lower" else (base - head)
        out["band_abs_pp"] = round(band, 3)
        out["worse_by_pp"] = round(worse_by, 3)
        out["regressed"] = worse_by > band
        return out
    scale = max(abs(base), 1e-12)
    band = max(rel_floor, _MAD_SCALE * mad / scale)
    worse_by = ((head - base) if direction == "lower"
                else (base - head)) / scale
    out["band_rel"] = round(band, 4)
    out["worse_by_rel"] = round(worse_by, 4)
    out["regressed"] = worse_by > band
    return out


def check(trajectory_paths, head_path: str | None = None,
          min_points: int = 1) -> dict:
    """The full sentinel pass. With ``head_path``, its rows are judged
    against the whole trajectory. Without it (audit mode — what the test
    suite runs over the committed repo files), every series' LATEST
    point is judged against that series' own earlier points, so each
    metric is covered regardless of which round's artifact carries it.
    """
    paths = list(trajectory_paths)
    judgements = {}
    regressions = []

    def judge_one(key, hist, head_val, unit):
        if len(hist) < min_points:
            judgements[key] = {
                "unit": unit, "head": head_val,
                "n_history": len(hist), "regressed": False,
                "skipped": f"history has {len(hist)} < {min_points} points"}
            return
        j = judge(hist, head_val, unit)
        judgements[key] = j
        if j["regressed"]:
            regressions.append(key)

    if head_path is not None:
        history = collect_series(paths)
        heads = collect_series([head_path])
        if not heads:
            # an empty/crashed head must FAIL the gate, not sail through
            # with zero judgements — the sentinel's own failure mode
            raise ValueError(
                f"no judgeable bench rows in head {head_path!r} — did the "
                "bench run crash? (error-unit rows are excluded)")
        for key, pts in heads.items():
            hist = [v for _, v, _ in history.get(key, [])]
            judge_one(key, hist, pts[-1][1], pts[-1][2])
    else:
        for key, pts in collect_series(paths).items():
            if len(pts) < 2:
                judgements[key] = {
                    "unit": pts[-1][2], "head": pts[-1][1],
                    "n_history": 0, "regressed": False,
                    "skipped": "single point — nothing to judge against"}
                continue
            last_round = max(r for r, _, _ in pts)
            head_pts = [p for p in pts if p[0] == last_round]
            hist = [v for r, v, _ in pts if r != last_round]
            judge_one(key, hist, head_pts[-1][1], head_pts[-1][2])
    return {
        "head": [head_path] if head_path else "per-series latest round",
        "trajectory": paths,
        "judgements": judgements,
        "regressions": sorted(regressions),
        "ok": not regressions,
    }


def selftest() -> int:
    """Calibration: the sentinel must FLAG a synthetic 2x slowdown and
    PASS a within-noise head, for both a throughput unit and a percent
    unit. Returns 0 on success (the CI gate runs this before trusting
    the real comparison)."""
    cases = [
        # (history, head, unit, must_flag)
        ([10.0, 10.3, 9.8], 5.0, "views/sec", True),     # 2x slowdown
        ([10.0, 10.3, 9.8], 9.6, "views/sec", False),    # noise
        ([1.2, 3.8], 100.0, "percent_overhead", True),   # 2x-slowdown arm
        ([1.2, 3.8], 6.0, "percent_overhead", False),    # noisy CI runner
        ([35.0, 40.0], 2.0, "percent_faster_with_pcpm", True),   # win lost
        ([35.0, 40.0], 55.0, "percent_faster_with_pcpm", False),  # bigger win
        ([1.6], 0.9, "x_fold_speedup", True),            # speedup lost
        ([0.02, 0.025], 0.05, "seconds", True),          # 2x slower view
        ([0.02, 0.025], 0.024, "seconds", False),
    ]
    failed = []
    for hist, head, unit, must_flag in cases:
        j = judge(hist, head, unit)
        if bool(j["regressed"]) != must_flag:
            failed.append((hist, head, unit, must_flag, j))
    for case in failed:
        print(f"perfwatch selftest FAILED: {case}", file=sys.stderr)
    print(f"perfwatch selftest: {len(cases) - len(failed)}/{len(cases)} "
          f"calibration cases behaved")
    return 1 if failed else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perfwatch",
        description="perf-regression sentinel over BENCH_*.json artifacts")
    ap.add_argument("trajectory", nargs="*",
                    help="trajectory artifacts/globs "
                         "(default: BENCH_*.json in cwd)")
    ap.add_argument("--head", default=None,
                    help="candidate artifact (bench JSON/JSONL); without "
                         "it the highest-round trajectory file is judged "
                         "against the earlier rounds")
    ap.add_argument("--report", default=None,
                    help="write the full judgement JSON here (CI artifact)")
    ap.add_argument("--min-points", type=int, default=1,
                    help="history points required before judging a metric")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in band calibration and exit")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()

    patterns = args.trajectory or ["BENCH_*.json"]
    paths = []
    for pat in patterns:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else ([pat] if os.path.exists(pat)
                                        else []))
    if not paths:
        print("perfwatch: no trajectory artifacts found", file=sys.stderr)
        return 2
    try:
        result = check(paths, head_path=args.head,
                       min_points=args.min_points)
    except (ValueError, OSError) as e:
        print(f"perfwatch: {e}", file=sys.stderr)
        return 2
    if args.report:
        with open(args.report, "w") as f:
            json.dump(result, f, indent=1)
    judged = [k for k, j in result["judgements"].items()
              if "skipped" not in j]
    print(f"perfwatch: {len(judged)} metrics judged, "
          f"{len(result['judgements']) - len(judged)} skipped, "
          f"{len(result['regressions'])} regressions")
    for key in result["regressions"]:
        j = result["judgements"][key]
        worse = j.get("worse_by_rel", j.get("worse_by_pp"))
        print(f"  REGRESSION {key}: head={j['head']} vs "
              f"median={j.get('baseline_median')} ({j['unit']}, "
              f"worse_by={worse})", file=sys.stderr)
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
