"""Interprocedural concurrency + taint rules (RT009–RT011, and the
cross-module halves of RT001/RT003/RT004).

These rules run over the :class:`~.interproc.Project` model: a resolved
call graph, inferred thread roots, and reaching locksets. Each encodes a
hazard the serving push (ROADMAP items 1/3/4) will otherwise mass-produce:
REST handler threads, job threads, fold workers, and the scrape thread all
share engine state that Akka actors isolated for free in the reference.

Precision-first like the per-module rules: anything the resolver is not
confident about is skipped, because the baseline is kept empty and every
finding costs a source fix or a reviewed pragma.
"""

from __future__ import annotations

import ast

from .findings import Finding
from .interproc import (FuncInfo, Project, _enclosing_class,
                        module_name_of)
from .rules import (Module, RULES, _dotted, _donated_positions,
                    _enclosing_def, _env_read_var, _is_cached_def,
                    _is_jit_call, _module_mutables, _parent, _traced_defs)

#: grow / shrink vocabulary for RT011
_GROW_METHODS = {"append", "add", "appendleft", "extend", "insert", "put",
                 "put_nowait", "setdefault", "update"}
_SHRINK_METHODS = {"pop", "popitem", "popleft", "clear", "remove", "discard",
                   "get_nowait", "task_done", "evict", "trim", "prune"}
_BOUND_KWARGS = {"maxlen", "maxsize"}

#: blocking boundaries for RT009 — the set the ISSUE names: device
#: transfers, compiles, sleeps, socket I/O. ``.wait``/``.result`` are
#: deliberately absent (condition waits RELEASE the lock; future results
#: are how the fold pipeline is built).
_BLOCKING_ATTRS = {"device_put", "device_get", "block_until_ready",
                   "accept", "create_connection", "getaddrinfo", "urlopen",
                   "recv", "recv_into", "sendall"}


def _chain_str(chain) -> str:
    return " -> ".join(f.label for f in chain)


def _finding(mod: Module, rule: str, node: ast.AST, message: str,
             symbol: str = "") -> Finding:
    line = getattr(node, "lineno", 1)
    return Finding(rule=rule, name=RULES[rule], path=mod.relpath, line=line,
                   col=getattr(node, "col_offset", 0) + 1, message=message,
                   symbol=symbol, line_text=mod.line_text(line))


def _qualname_of(mod: Module, node: ast.AST) -> str:
    names = []
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = _parent(cur)
    return ".".join(reversed(names))


def _is_blocking_call(node: ast.Call) -> str | None:
    """A short label when ``node`` is a blocking boundary, else None."""
    func = node.func
    dotted = _dotted(func)
    tail = dotted.split(".")[-1] if dotted else ""
    if tail == "sleep":
        base = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        if base in ("time", ""):
            return "time.sleep"
    if tail in _BLOCKING_ATTRS:
        return tail
    # fn.lower(*args).compile() — the AOT compile boundary
    if isinstance(func, ast.Attribute) and func.attr == "compile" and \
            isinstance(func.value, ast.Call) and \
            isinstance(func.value.func, ast.Attribute) and \
            func.value.func.attr == "lower":
        return "lower().compile"
    return None


# ---------------------------------------------------------------------------
# RT009 blocking-call-under-lock


def check_blocking_under_lock(project: Project) -> list[Finding]:
    """A blocking boundary (``device_put``/``device_get``/
    ``block_until_ready``/AOT compile/``time.sleep``/socket I/O) reachable
    while a lock is held: every thread queued on that lock inherits the
    stall — multi-second on a flapping interconnect (the runtime
    sanitizer's lock-across-device-boundary finding, caught at lint time
    and through call chains)."""
    out: list[Finding] = []
    reported: set = set()

    def visit(fn: FuncInfo, node, locks, chain):
        if not locks or not isinstance(node, ast.Call):
            return
        label = _is_blocking_call(node)
        if label is None:
            return
        key = (id(node), frozenset(locks))
        if key in reported:
            return
        reported.add(key)
        sites = ", ".join(sorted(locks))
        path = _chain_str(chain)
        out.append(_finding(
            fn.mod, "RT009", node,
            f"blocking call {label}() reachable while lock(s) [{sites}] "
            f"held (path: {path}) — every thread queued on the lock "
            f"inherits the stall; move the blocking work outside the "
            f"critical section",
            symbol=_qualname_of(fn.mod, node)))

    # one shared memo: every (function, lockset) context is walked once
    # across the all-functions sweep, keeping the pass linear
    memo: set = set()
    for fi in sorted(project.functions.values(),
                     key=lambda f: (f.mod.relpath, f.node.lineno)):
        project.walk_from(fi, visit, seen=memo)
    return _dedupe(out)


# ---------------------------------------------------------------------------
# RT010 shared-state-without-common-lock


def check_shared_state_locksets(project: Project) -> list[Finding]:
    """Shared state written from thread-root call chains whose guarding
    locksets have an EMPTY intersection. Tracked state: module-level
    names (container mutations AND bare rebinds — the check-then-set lazy
    singleton is the motivating shape) and instance-attribute *container*
    mutations outside ``__init__`` (scalar instance rebinds are
    GIL-atomic publish/handoff idioms and stay exempt). All inferred
    roots count as multi-instance: two REST handler threads, two
    executor workers, or two job threads already race each other, so one
    unguarded write site is enough."""
    roots = project.thread_roots()
    if not roots:
        return []
    # key → list of (lockset, node, mod, root_label)
    writes: dict[tuple, list] = {}
    mutables_by_mod = {module_name_of(m.relpath): _module_mutables(m)
                       for m in project.modules}
    globals_cache: dict[int, set] = {}

    def globals_of(fn_node) -> set:
        g = globals_cache.get(id(fn_node))
        if g is None:
            g = {n for stmt in ast.walk(fn_node)
                 if isinstance(stmt, ast.Global) for n in stmt.names}
            globals_cache[id(fn_node)] = g
        return g

    def classify(fn: FuncInfo, node) -> tuple | None:
        mod_name = module_name_of(fn.mod.relpath)
        in_init = fn.qualname.endswith("__init__")
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                # module global rebinds need an explicit `global` decl
                if isinstance(t, ast.Name) and \
                        t.id in globals_of(fn.node):
                    return ("g", mod_name, t.id)
                if isinstance(t, ast.Subscript):
                    base = t.value
                    if isinstance(base, ast.Name) and \
                            base.id in mutables_by_mod.get(mod_name, ()) \
                            and not _locals_of(fn.node, base.id):
                        return ("g", mod_name, base.id)
                    dotted = _dotted(base)
                    if dotted.startswith("self.") and \
                            dotted.count(".") == 1 and not in_init:
                        cls = _enclosing_class(fn.node)
                        if cls is not None and \
                                project._attr_is_container(
                                    mod_name, cls.name,
                                    dotted.split(".")[1]):
                            return ("a", mod_name, cls.name,
                                    dotted.split(".")[1])
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _GROW_METHODS | _SHRINK_METHODS:
            base = node.func.value
            if isinstance(base, ast.Name) and \
                    base.id in mutables_by_mod.get(mod_name, ()) and \
                    not _locals_of(fn.node, base.id):
                return ("g", mod_name, base.id)
            dotted = _dotted(base)
            if dotted.startswith("self.") and dotted.count(".") == 1 and \
                    not in_init:
                cls = _enclosing_class(fn.node)
                if cls is not None and project._attr_is_container(
                        mod_name, cls.name, dotted.split(".")[1]):
                    return ("a", mod_name, cls.name,
                            dotted.split(".")[1])
        return None

    for root in roots:
        def visit(fn: FuncInfo, node, locks, chain, _root=root):
            key = classify(fn, node)
            if key is None:
                return
            writes.setdefault(key, []).append(
                (frozenset(locks), node, fn.mod, _root.label))
        # spawns are NOT followed here: a write after a Thread/submit
        # boundary belongs to the SPAWNED root (walked separately), and
        # attributing it to the spawner would flag per-instance state a
        # job thread confines to itself (Job.results)
        project.walk_from(root.fn, visit, follow_spawns=False)

    out: list[Finding] = []
    for key, recs in sorted(writes.items()):
        locksets = [r[0] for r in recs]
        common = frozenset.intersection(*locksets) if locksets else frozenset()
        if common:
            continue
        kind = key[0]
        if kind == "a":
            # instance attrs: require two DISTINCT root functions — a
            # single root writing its own per-instance state (Job.results
            # from the job's own thread) is confinement, not sharing
            if len({r[3] for r in recs}) < 2:
                continue
        recs.sort(key=lambda r: (r[2].relpath, r[1].lineno))
        node, mod = recs[0][1], recs[0][2]
        name = key[2] if kind == "g" else f"{key[2]}.{key[3]}"
        root_labels = sorted({r[3] for r in recs})
        seen_sets = sorted({("{" + ", ".join(sorted(s)) + "}") if s
                            else "{}" for s, *_ in recs})
        out.append(_finding(
            mod, "RT010", node,
            f"shared state {name!r} is written from thread root(s) "
            f"{', '.join(root_labels)} with no common lock (locksets "
            f"seen: {', '.join(seen_sets)}) — writes race; guard every "
            f"write site with one lock",
            symbol=_qualname_of(mod, node)))
    return _dedupe(out)


def _locals_of(fn_node, name: str) -> set[str]:
    """{name} when ``name`` is function-local in ``fn_node`` (assigned
    without a ``global`` declaration), else empty."""
    declared_global = any(isinstance(n, ast.Global) and name in n.names
                          for n in ast.walk(fn_node))
    if declared_global:
        return set()
    assigned = any(isinstance(n, ast.Name) and n.id == name
                   and isinstance(n.ctx, ast.Store)
                   for n in ast.walk(fn_node))
    return {name} if assigned else set()


# ---------------------------------------------------------------------------
# RT011 unbounded-growth-on-request-path


def check_unbounded_growth(project: Project) -> list[Finding]:
    """A long-lived container (module global or instance attribute
    assigned in ``__init__``) that GROWS on a REST-request-reachable path
    — through thread/executor spawns, the way a submitted job is request
    work — with no shrink operation anywhere in the project and no
    construction-time bound (``deque(maxlen=…)``, ``Queue(maxsize=…)``):
    memory scales with requests served, the classic serving slow leak."""
    roots = [r for r in project.thread_roots() if r.kind == "rest-handler"]
    if not roots:
        return []

    # --- candidate containers and their project-wide grow/shrink sites
    grows: dict[tuple, list] = {}     # key → [(node, mod, chain)]
    shrinks: set = set()
    bounded: set = set()

    def container_key(fn: FuncInfo, base: ast.AST):
        mod_name = module_name_of(fn.mod.relpath)
        if isinstance(base, ast.Name):
            if base.id in _module_mutables(fn.mod) and \
                    not _locals_of(fn.node, base.id):
                return ("g", mod_name, base.id)
            return None
        dotted = _dotted(base)
        if dotted.startswith("self.") and dotted.count(".") == 1:
            cls = _enclosing_class(fn.node)
            if cls is not None and project._attr_is_container(
                    mod_name, cls.name, dotted.split(".")[1]):
                return ("a", mod_name, cls.name, dotted.split(".")[1])
        return None

    # project-wide shrink/bound scan (not just request-reachable paths:
    # an evictor on ANY path bounds the container)
    for m in project.modules:
        mod_name = module_name_of(m.relpath)
        for node in ast.walk(m.tree):
            fn_node = _enclosing_def(node)
            if fn_node is None:
                continue
            fi = project.functions.get(
                (m.relpath, _qualname_of(m, fn_node)))
            if fi is None:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SHRINK_METHODS:
                key = container_key(fi, node.func.value)
                if key is not None:
                    shrinks.add(key)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        key = container_key(fi, t.value)
                        if key is not None:
                            shrinks.add(key)
            elif isinstance(node, ast.Assign):
                # re-assigning the slot outside __init__ resets/trims it
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            _dotted(t).startswith("self.") and \
                            not fi.qualname.endswith("__init__"):
                        cls = _enclosing_class(fi.node)
                        if cls is not None:
                            shrinks.add(("a", mod_name, cls.name, t.attr))
                    elif isinstance(t, ast.Name) and fn_node is not None \
                            and _locals_of(fn_node, t.id) == set() and \
                            any(isinstance(g, ast.Global)
                                and t.id in g.names
                                for g in ast.walk(fn_node)):
                        shrinks.add(("g", mod_name, t.id))

    # construction-time bounds (Assign AND AnnAssign — the module-level
    # ring idiom is ``_RECENT: deque = deque(maxlen=64)``)
    for m in project.modules:
        mod_name = module_name_of(m.relpath)
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            call = value
            has_bound = any(kw.arg in _BOUND_KWARGS
                            for kw in call.keywords) or \
                (_dotted(call.func).split(".")[-1] == "deque"
                 and len(call.args) >= 2) or \
                (_dotted(call.func).split(".")[-1].endswith("Queue")
                 and bool(call.args))
            if not has_bound:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and \
                        isinstance(_parent(node), ast.Module):
                    bounded.add(("g", mod_name, t.id))
                elif isinstance(t, ast.Attribute) and \
                        _dotted(t).startswith("self."):
                    fn_node = _enclosing_def(node)
                    cls = _enclosing_class(fn_node) if fn_node else None
                    if cls is not None:
                        bounded.add(("a", mod_name, cls.name, t.attr))

    # request-reachable grow sites. AugAssign is NOT growth: x[k] += 1
    # updates an existing cell (a missing key raises on the read) — the
    # module-level [0]-counter idiom must stay clean.
    for root in roots:
        def visit(fn: FuncInfo, node, locks, chain, _root=root):
            key = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _GROW_METHODS:
                key = container_key(fn, node.func.value)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        key = container_key(fn, t.value)
            if key is not None:
                grows.setdefault(key, []).append((node, fn.mod, chain))
        project.walk_from(root.fn, visit, follow_spawns=True)

    out: list[Finding] = []
    for key, sites in sorted(grows.items()):
        if key in shrinks or key in bounded:
            continue
        sites.sort(key=lambda s: (s[1].relpath, s[0].lineno))
        node, mod, chain = sites[0]
        name = key[2] if key[0] == "g" else f"{key[2]}.{key[3]}"
        out.append(_finding(
            mod, "RT011", node,
            f"{name!r} grows on a request-reachable path (path: "
            f"{_chain_str(chain)}) and nothing in the project ever "
            f"shrinks or bounds it — memory scales with requests served; "
            f"add an eviction policy, a cap, or a bounded container",
            symbol=_qualname_of(mod, node)))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# interprocedural RT001 env-not-in-cache-key


def check_env_in_cache_key_project(project: Project) -> list[Finding]:
    """The RT001 walk, project-wide: an env read reachable from an
    ``lru_cache``'d function through ANY resolvable call chain — module
    helpers (the PR 4 scope) and now cross-module helpers too (the
    ``utils/config`` idiom). Calls into OTHER cached factories are not
    followed: the callee's own cache key is its own rule instance."""
    out: list[Finding] = []
    cached = [fi for fi in project.functions.values()
              if _is_cached_def(fi.node)]

    for root in sorted(cached, key=lambda f: (f.mod.relpath,
                                              f.node.lineno)):
        seen_nodes: set = set()

        def visit(fn: FuncInfo, node, locks, chain, _root=root):
            var = _env_read_var(node)
            if var is None or id(node) in seen_nodes:
                return
            seen_nodes.add(id(node))
            label = var or "<dynamic>"
            where = ""
            if fn.mod.relpath != _root.mod.relpath:
                where = f" via {_chain_str(chain)}"
            out.append(_finding(
                fn.mod, "RT001", node,
                f"env knob {label!r} read inside code reachable from "
                f"lru_cache'd {_root.node.name!r}{where} — the knob is "
                f"not part of the cache key; pass it as an argument "
                f"instead",
                symbol=_qualname_of(fn.mod, node)))

        project.walk_from(
            root, visit, max_depth=6,
            follow_filter=lambda fi, _root=root: (
                fi is _root or not _is_cached_def(fi.node)))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# interprocedural RT003 host-sync-in-trace


def _host_sync_label(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Attribute):
        base = _dotted(node.func.value)
        if node.func.attr in ("item", "block_until_ready") and \
                not base.startswith(("np", "numpy")):
            return f".{node.func.attr}() forces a device→host sync"
        if node.func.attr in ("asarray", "array") and \
                base in ("np", "numpy"):
            return (f"{base}.{node.func.attr}() materialises a tracer "
                    f"on the host")
        if node.func.attr == "device_get":
            return "device_get() forces a device→host sync"
    return None


def check_host_sync_in_trace_project(project: Project) -> list[Finding]:
    """RT003 through call chains: a helper containing a host-sync
    primitive called (transitively) from a jit-traced body is traced too
    — the sync fires at trace time no matter which module the helper
    lives in. Only plain defs are followed (a callee that is itself a
    compiled-program factory returns a callable; it is not inlined)."""
    out: list[Finding] = []

    def plain(fi: FuncInfo) -> bool:
        return not _is_cached_def(fi.node) and not any(
            _is_jit_call(n) for n in ast.walk(fi.node)
            if isinstance(n, ast.Call))

    for m in project.modules:
        for traced in _traced_defs(m):
            root = project.functions.get(
                (m.relpath, _qualname_of(m, traced)))
            if root is None:
                continue

            def visit(fn: FuncInfo, node, locks, chain, _root=root):
                if fn is _root or not isinstance(node, ast.Call):
                    return   # the per-module rule owns the root body
                msg = _host_sync_label(node)
                if msg is None:
                    return
                out.append(_finding(
                    fn.mod, "RT003", node,
                    f"{msg} inside {fn.label!r}, reached from jit-traced "
                    f"{_root.node.name!r} (path: {_chain_str(chain)}) — "
                    f"hoist it out of the traced call chain",
                    symbol=_qualname_of(fn.mod, node)))

            project.walk_from(root, visit, max_depth=4,
                              follow_filter=lambda fi, _r=root:
                              fi is _r or plain(fi))
    return _dedupe(out)


# ---------------------------------------------------------------------------
# interprocedural RT004 use-after-donate


def donating_factories_project(project: Project) -> dict:
    """(module relpath, factory name) → donated positions, for every
    module function returning ``jax.jit(..., donate_argnums=…)`` (the
    ledger ``instrument()`` wrapper unwrapped, as in the per-module
    rule)."""
    out: dict = {}
    for fi in project.functions.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Return) or \
                    not isinstance(node.value, ast.Call):
                continue
            jit_call = None
            if _is_jit_call(node.value):
                jit_call = node.value
            else:
                for arg in node.value.args:
                    if isinstance(arg, ast.Call) and _is_jit_call(arg):
                        jit_call = arg
                        break
            if jit_call is None:
                continue
            pos = _donated_positions(jit_call)
            if pos:
                out[(fi.mod.relpath, fi.node.name)] = pos
    return out


def check_use_after_donate_project(project: Project) -> list[Finding]:
    """RT004 through imports: a donating factory defined in ANOTHER
    module (``from ..engine.device_sweep import _compiled_apply``) must
    taint its call sites the same way a module-local one does. Module-
    local bindings are owned by the per-module rule and skipped here."""
    from .rules import _donate_flow, _donor_bindings

    factories = donating_factories_project(project)
    out: list[Finding] = []
    for fi in sorted(project.functions.values(),
                     key=lambda f: (f.mod.relpath, f.node.lineno)):
        mod = fi.mod

        def resolve(call, _mod=mod):
            callee = project.resolve_call(_mod, _enclosing_def(call), call)
            if callee is None or callee.mod is _mod:
                return None   # same-module factories: per-module rule
            return factories.get((callee.mod.relpath, callee.node.name))

        donors = _donor_bindings(fi.node, {}, resolve=resolve)
        out.extend(_donate_flow(mod, fi.node, donors))
    return _dedupe(out)


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
