"""Process bootstrap + device topology discovery.

The reference boots an Akka ActorSystem per process with seed-node or
kubernetes discovery (``DocSvr.scala:39-58``) and Netty TCP remoting. The
TPU-native equivalent is the JAX distributed runtime: one call per host
wires the control plane (gRPC) and makes every chip in the slice/pod
visible as a global device — all data-plane traffic then rides ICI/DCN
inside compiled programs, not a message broker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import jax

from ..obs.trace import TRACER


def bootstrap(coordinator_address: str | None = None,
              num_processes: int | None = None,
              process_id: int | None = None) -> bool:
    """Initialise the multi-host JAX runtime (idempotent).

    No arguments → values come from the environment the way cloud TPU
    runtimes inject them (the reference reads HOST_IP/seed lists the same
    way, ``ConfigUtils.scala:19-34``). Single-process deployments (the
    reference's ``SingleNodeSetup``) skip initialisation entirely: returns
    False when there is nothing to join.

    On success the process tracer learns ``jax.process_index()`` — every
    captured ``TraceContext`` then carries this process as its origin and
    every ``superstep``/``comm.*`` span is tagged ``process=``.
    """
    if num_processes is None and coordinator_address is None and \
            "JAX_COORDINATOR_ADDRESS" not in os.environ and \
            "COORDINATOR_ADDRESS" not in os.environ:
        return False  # single-process mode
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        TRACER.set_process_index(jax.process_index())
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            TRACER.set_process_index(jax.process_index())
            return True
        raise


@dataclass(frozen=True)
class Topology:
    """What the mesh builder needs to know about this deployment — plus
    where every peer's REST plane listens (``peers``), so ``/clusterz``
    federation needs no hand-wired peer list on a strided localhost
    cluster."""

    n_devices: int
    n_local_devices: int
    n_processes: int
    process_id: int
    platform: str
    #: per-process REST base URLs derived from the port-striding scheme
    #: (index i binds rest_port + i x RTPU_PORT_STRIDE on peer_host) —
    #: RTPU_CLUSTER_PEERS overrides for non-localhost deployments
    peers: tuple = field(default=())

    @property
    def multi_host(self) -> bool:
        return self.n_processes > 1


def peer_urls(n_processes: int, rest_port: int | None = None,
              host: str | None = None) -> tuple:
    """The deployment's per-process REST base URLs, in process order.

    ``RTPU_CLUSTER_PEERS`` (comma-separated ``host:port`` / URLs, or
    ``@/path/to/peers.txt`` one-per-line) wins when set — real multi-host
    deployments name their peers. Otherwise the bootstrap topology is
    enough: peer ``i`` listens on ``rest_port + i * RTPU_PORT_STRIDE``
    (utils/config.strided_port) on ``RTPU_PEER_HOST`` (default
    127.0.0.1 — the N-process localhost cluster). One definition:
    ``obs/cluster.resolve_peers`` (stdlib-only; /clusterz shares it)."""
    from ..obs.cluster import resolve_peers

    return resolve_peers(n_processes, rest_port, host)


def topology(rest_port: int | None = None) -> Topology:
    devs = jax.devices()
    n_proc = jax.process_count()
    return Topology(
        n_devices=len(devs),
        n_local_devices=len(jax.local_devices()),
        n_processes=n_proc,
        process_id=jax.process_index(),
        platform=devs[0].platform if devs else "none",
        peers=peer_urls(n_proc, rest_port),
    )
