"""Process bootstrap + device topology discovery.

The reference boots an Akka ActorSystem per process with seed-node or
kubernetes discovery (``DocSvr.scala:39-58``) and Netty TCP remoting. The
TPU-native equivalent is the JAX distributed runtime: one call per host
wires the control plane (gRPC) and makes every chip in the slice/pod
visible as a global device — all data-plane traffic then rides ICI/DCN
inside compiled programs, not a message broker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax


def bootstrap(coordinator_address: str | None = None,
              num_processes: int | None = None,
              process_id: int | None = None) -> bool:
    """Initialise the multi-host JAX runtime (idempotent).

    No arguments → values come from the environment the way cloud TPU
    runtimes inject them (the reference reads HOST_IP/seed lists the same
    way, ``ConfigUtils.scala:19-34``). Single-process deployments (the
    reference's ``SingleNodeSetup``) skip initialisation entirely: returns
    False when there is nothing to join.
    """
    if num_processes is None and coordinator_address is None and \
            "JAX_COORDINATOR_ADDRESS" not in os.environ and \
            "COORDINATOR_ADDRESS" not in os.environ:
        return False  # single-process mode
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise


@dataclass(frozen=True)
class Topology:
    """What the mesh builder needs to know about this deployment."""

    n_devices: int
    n_local_devices: int
    n_processes: int
    process_id: int
    platform: str

    @property
    def multi_host(self) -> bool:
        return self.n_processes > 1


def topology() -> Topology:
    devs = jax.devices()
    return Topology(
        n_devices=len(devs),
        n_local_devices=len(jax.local_devices()),
        n_processes=jax.process_count(),
        process_id=jax.process_index(),
        platform=devs[0].platform if devs else "none",
    )
