"""Node assembly — the ``RaphtoryReplicator`` / ``SingleNodeSetup`` analogue.

The reference's per-node factory builds the role's component stack once the
WatchDog grants an id (``RaphtoryReplicator.scala:124-168``), and
``SingleNodeSetup`` co-locates every role in one process for the
single-node deployment (``singlenode/SingleNodeSetup.scala:32-40``).
``NodeRuntime`` is both: it assembles ingestion + storage + analysis + REST
+ metrics + the archivist cycle behind one object with lifecycle methods,
wiring heartbeats and the memory governor through the shared scheduler.
"""

from __future__ import annotations

from ..core.service import TemporalGraph
from ..ingestion.pipeline import IngestionPipeline
from ..jobs.manager import AnalysisManager
from ..persist.compaction import Archivist
from ..utils.config import Settings
from ..utils.scheduler import Scheduler
from .bootstrap import bootstrap, topology
from .watchdog import WatchDog

import logging

_log = logging.getLogger(__name__)


class NodeRuntime:
    def __init__(self, settings: Settings | None = None, mesh=None,
                 watchdog: WatchDog | None = None):
        self.settings = settings or Settings()
        self.watchdog = watchdog or WatchDog(self.settings)
        self.scheduler = Scheduler()
        self.multi_host = bootstrap() if not self.settings.local else False
        self.topology = topology(self.settings.rest_port)
        # restore-a-dead-shard: a replacement node with the same checkpoint
        # dir rehydrates the log before serving (the reference designed this
        # via Cassandra + SAVING, Utils.scala:22; here persist/checkpoint)
        restored = None
        if self.settings.checkpoint_dir:
            import os

            p = self.checkpoint_path()
            if os.path.exists(p):
                from ..persist.checkpoint import load_log

                restored = load_log(p)
        self.graph = TemporalGraph(restored)
        self.pipeline = IngestionPipeline(
            log=self.graph.log, watermarks=self.graph.watermarks,
            queue_max_events=self.settings.ingest_queue_events)
        self.mesh = mesh
        self.manager = AnalysisManager(
            self.graph, mesh=mesh, sink_dir=self.settings.sink_dir,
            sink_format=self.settings.sink_format)
        self.archivist = Archivist(
            self.graph, max_events=self.settings.max_events,
            archive_fraction=self.settings.archive_fraction,
            compressing=self.settings.compressing,
            archiving=self.settings.archiving)
        self._rest = None
        self._metrics = None
        self._members: list[tuple[str, int]] = []  # (role, id) this node owns

    # ---- lifecycle ----

    def start(self, rest: bool = False, metrics: bool = False) -> "NodeRuntime":
        s = self.settings
        self._members.append(("shard", self.watchdog.join("shard")))
        self._members.append(("job-server", self.watchdog.join("job-server")))
        self.scheduler.recurring(
            "keep-alive", s.heartbeat_interval_s, self._beat_all)
        if s.archiving or s.compressing:
            self.scheduler.recurring(
                "archivist", s.archivist_interval_s,
                self.archivist.maybe_compact)
        if s.saving and s.checkpoint_dir:
            # the SAVING flag's durable-history cycle (Utils.scala:22)
            self.scheduler.recurring(
                "checkpoint", s.archivist_interval_s, self.checkpoint)
        if rest:
            from ..jobs.rest import RestServer

            self._rest = RestServer(self.manager, port=s.rest_port,
                                    watchdog=self.watchdog).start()
        if metrics:
            from ..obs.metrics import MetricsServer

            self._metrics = MetricsServer(port=s.metrics_port).start()
        return self

    def _beat_all(self) -> None:
        for role, cid in self._members:
            self.watchdog.beat(role, cid)

    def add_source(self, source, parser=None) -> None:
        """Register + start consuming a source (a Spout joining the
        cluster: id assignment then the stateCheck gate)."""
        self._members.append(("source", self.watchdog.join("source")))
        self.pipeline.add_source(source, parser)

    def ingest(self, wait: bool = True) -> None:
        self.pipeline.start()
        if wait:
            self.pipeline.join()
            if self.settings.prewarm:
                self.prewarm()
        elif self.settings.prewarm:
            # serve path: chase the pipeline from a side thread so the
            # FIRST REST View still lands on a pinned sweep
            import threading

            threading.Thread(
                target=lambda: (self.pipeline.join(), self.prewarm(True)),
                name="prewarm-after-ingest", daemon=True).start()

    def prewarm(self, block: bool = False) -> None:
        """Pin the resident View sweep now (background by default) so the
        first View/Live query runs the warm path instead of paying the
        table build + upload + compile. Device trouble during the pin is
        logged and dropped — queries then just take the cold path."""
        import threading

        def _pin():
            try:
                t = min(self.graph.safe_time(), self.graph.latest_time)
                if t < -(2**61):
                    return   # empty graph: nothing to pin
                acq = self.graph.resident_acquire(int(t))
                if acq is not None:
                    sweep, lock = acq
                    try:
                        sweep.advance(int(t))
                    except Exception:
                        self.graph.resident_discard()
                    finally:
                        lock.release()
            except Exception:
                # same failure mode jobs/manager.py guards: DeviceSweep
                # construction can raise on device trouble mid-upload
                _log.warning("prewarm pin failed; queries will run cold",
                             exc_info=True)

        if block:
            _pin()
        else:
            threading.Thread(target=_pin, name="prewarm",
                             daemon=True).start()

    def submit(self, program, query):
        return self.manager.submit(program, query)

    def checkpoint_path(self) -> str:
        import os

        return os.path.join(self.settings.checkpoint_dir, "node.npz")

    def checkpoint(self) -> None:
        """Durable snapshot of the node's log (atomic tmp+rename; safe
        during live ingestion — save_log freezes first)."""
        import os

        from ..persist.checkpoint import save_log

        os.makedirs(self.settings.checkpoint_dir, exist_ok=True)
        save_log(self.graph.log, self.checkpoint_path())

    def stop(self) -> None:
        self.pipeline.stop()
        self.scheduler.shutdown()
        if self._rest is not None:
            self._rest.stop()
        if self._metrics is not None:
            self._metrics.stop()
