"""Cluster management — control plane (reference L6, SURVEY §2.6, §5.3).

The reference's control plane is actor-based: a ``WatchDog`` singleton
assigns dense ids, tallies keep-alives and gates the cluster-up transition;
``SeedActor``/``DocSvr`` handle membership; ``RaphtoryReplicator`` builds
each node's component stack. Here the data plane is XLA collectives inside
one SPMD program, so the control plane shrinks to: process bootstrap
(:mod:`.bootstrap` over the JAX distributed runtime), component liveness +
cluster-up gating (:mod:`.watchdog`), and node assembly (:mod:`.runtime`).
"""

from .bootstrap import bootstrap, topology
from .runtime import NodeRuntime
from .watchdog import WatchDog

__all__ = ["WatchDog", "NodeRuntime", "bootstrap", "topology"]
