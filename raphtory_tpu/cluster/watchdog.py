"""WatchDog — liveness registry, dense id assignment, cluster-up gate.

Parity with ``WatchDog.scala``: joining components request an id and get a
dense one back (``RequestPartitionId`` → ``AssignedId``, lines 116-131);
keep-alives land in per-role maps (104-153); ``cluster_up`` flips once
enough of every role is present (66-83); stale members are flagged after
``stale_after_s`` (26-31 staleness logging) and auto-downed after
``auto_down_after_s`` (the Akka ``auto-down-unreachable-after`` analogue,
application.conf:152). Elastic growth parity: ids only grow, and observers
can subscribe to component-count changes (``PartitionsCount`` republish).
"""

from __future__ import annotations

import threading
import time as _time

from ..utils.config import Settings


class WatchDog:
    ROLES = ("shard", "source", "job-server")

    def __init__(self, settings: Settings | None = None, clock=_time.monotonic):
        self.settings = settings or Settings()
        self._clock = clock
        self._lock = threading.Lock()
        self._next_id: dict[str, int] = {r: 0 for r in self.ROLES}
        self._beats: dict[tuple[str, int], float] = {}
        self._down: set[tuple[str, int]] = set()
        self._watchers: list = []

    # ---- id assignment (RequestPartitionId → AssignedId) ----

    def join(self, role: str) -> int:
        """Register a component; returns its dense id. Counts only grow —
        the reference's elasticity contract (WatchDog.scala:116-124)."""
        if role not in self._next_id:
            raise ValueError(f"unknown role {role!r}; roles={self.ROLES}")
        with self._lock:
            cid = self._next_id[role]
            self._next_id[role] += 1
            self._beats[(role, cid)] = self._clock()
            watchers = list(self._watchers)
            count = self._next_id[role]
        for w in watchers:  # PartitionsCount republish analogue
            w(role, count)
        return cid

    def watch_counts(self, fn) -> None:
        """Subscribe to (role, new_count) growth events (UpdatedCounter)."""
        with self._lock:
            self._watchers.append(fn)

    # ---- keep-alives ----

    def beat(self, role: str, cid: int) -> bool:
        """Refresh a member's keep-alive. Beats from ids that never
        ``join``ed are rejected (returns False) — an unknown sender must
        not conjure a live member into the quorum counts."""
        with self._lock:
            key = (role, cid)
            if key not in self._beats:
                return False
            if key in self._down:   # a member that beats again rejoins
                self._down.discard(key)
            self._beats[key] = self._clock()
            return True

    def members(self, role: str | None = None) -> list[tuple[str, int]]:
        with self._lock:
            return sorted(k for k in self._beats
                          if k not in self._down
                          and (role is None or k[0] == role))

    # ---- health ----

    def stale(self) -> list[tuple[str, int, float]]:
        """(role, id, seconds-silent) for members past the staleness bar."""
        now = self._clock()
        bar = self.settings.stale_after_s
        with self._lock:
            return sorted(
                (r, c, now - t) for (r, c), t in self._beats.items()
                if (r, c) not in self._down and now - t > bar)

    def auto_down(self) -> list[tuple[str, int]]:
        """Mark members silent past ``auto_down_after_s`` as down; returns
        the newly downed set. Down members drop out of cluster_up counts
        until they beat again."""
        now = self._clock()
        bar = self.settings.auto_down_after_s
        newly = []
        with self._lock:
            for key, t in self._beats.items():
                if key not in self._down and now - t > bar:
                    self._down.add(key)
                    newly.append(key)
        return sorted(newly)

    # ---- cluster-up gate (WatchDog.scala:66-83) ----

    def cluster_up(self) -> bool:
        with self._lock:
            alive = [k for k in self._beats if k not in self._down]
            shards = sum(1 for r, _ in alive if r == "shard")
            sources = sum(1 for r, _ in alive if r == "source")
        return (shards >= self.settings.min_shards
                and sources >= self.settings.min_sources)

    def await_up(self, timeout_s: float = 60.0, poll_s: float = 0.05) -> bool:
        """Block until cluster_up (the Spout 'stateCheck' poll loop,
        SpoutTrait.scala:70-88)."""
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.cluster_up():
                return True
            _time.sleep(poll_s)
        return self.cluster_up()
