"""WatchDog — liveness registry, dense id assignment, cluster-up gate.

Parity with ``WatchDog.scala``: joining components request an id and get a
dense one back (``RequestPartitionId`` → ``AssignedId``, lines 116-131);
keep-alives land in per-role maps (104-153); ``cluster_up`` flips once
enough of every role is present (66-83); stale members are flagged after
``stale_after_s`` (26-31 staleness logging) and auto-downed after
``auto_down_after_s`` (the Akka ``auto-down-unreachable-after`` analogue,
application.conf:152). Elastic growth parity: ids only grow, and observers
can subscribe to component-count changes (``PartitionsCount`` republish).

Control-plane observability: every membership transition (join, stale,
auto-down, rejoin-after-down) lands as a flight-recorder instant
(``cluster.join`` / ``cluster.stale`` / ``cluster.auto_down`` /
``cluster.rejoin``) and refreshes the ``raphtory_cluster_members{role}``
and ``raphtory_cluster_stale_members`` gauges — what ``/statusz`` embeds
per process and ``/clusterz`` federates across the deployment. Instants
and gauge pushes happen OUTSIDE the registry lock: the telemetry layer
must never extend this hot mutex's hold time (or deadlock through a
metrics callback).
"""

from __future__ import annotations

import threading
import time as _time

from ..obs.metrics import METRICS
from ..obs.trace import TRACER
from ..utils.config import Settings


class WatchDog:
    ROLES = ("shard", "source", "job-server")

    def __init__(self, settings: Settings | None = None, clock=_time.monotonic):
        self.settings = settings or Settings()
        self._clock = clock
        self._lock = threading.Lock()
        self._next_id: dict[str, int] = {r: 0 for r in self.ROLES}
        self._beats: dict[tuple[str, int], float] = {}
        self._down: set[tuple[str, int]] = set()
        self._watchers: list = []
        # members already reported stale — each staleness EPISODE emits
        # one instant, not one per stale() poll
        self._stale_flagged: set[tuple[str, int]] = set()

    # ---- telemetry (all outside the lock) ----

    def _counts_locked(self) -> tuple[dict[str, int], int]:
        """(live members per role, stale count) — caller holds _lock."""
        now = self._clock()
        bar = self.settings.stale_after_s
        counts = {r: 0 for r in self.ROLES}
        stale = 0
        for (r, c), t in self._beats.items():
            if (r, c) in self._down:
                continue
            counts[r] = counts.get(r, 0) + 1
            if now - t > bar:
                stale += 1
        return counts, stale

    @staticmethod
    def _push_gauges(counts: dict[str, int], stale: int) -> None:
        for role, n in counts.items():
            METRICS.cluster_members.labels(role).set(n)
        METRICS.cluster_stale.set(stale)

    # ---- id assignment (RequestPartitionId → AssignedId) ----

    def join(self, role: str) -> int:
        """Register a component; returns its dense id. Counts only grow —
        the reference's elasticity contract (WatchDog.scala:116-124)."""
        if role not in self._next_id:
            raise ValueError(f"unknown role {role!r}; roles={self.ROLES}")
        with self._lock:
            cid = self._next_id[role]
            self._next_id[role] += 1
            self._beats[(role, cid)] = self._clock()
            watchers = list(self._watchers)
            count = self._next_id[role]
            counts, stale = self._counts_locked()
        self._push_gauges(counts, stale)
        TRACER.instant("cluster.join", role=role, id=cid,
                       members=counts.get(role, 0))
        for w in watchers:  # PartitionsCount republish analogue
            w(role, count)
        return cid

    def watch_counts(self, fn) -> None:
        """Subscribe to (role, new_count) growth events (UpdatedCounter)."""
        with self._lock:
            self._watchers.append(fn)

    # ---- keep-alives ----

    def beat(self, role: str, cid: int) -> bool:
        """Refresh a member's keep-alive. Beats from ids that never
        ``join``ed are rejected (returns False) — an unknown sender must
        not conjure a live member into the quorum counts."""
        rejoined = recovered = False
        with self._lock:
            key = (role, cid)
            if key not in self._beats:
                return False
            if key in self._down:   # a member that beats again rejoins
                self._down.discard(key)
                rejoined = True
            if key in self._stale_flagged:   # staleness episode over
                self._stale_flagged.discard(key)
                recovered = True
            self._beats[key] = self._clock()
            if rejoined or recovered:
                counts, stale = self._counts_locked()
        if rejoined or recovered:
            self._push_gauges(counts, stale)
        if rejoined:
            TRACER.instant("cluster.rejoin", role=role, id=cid)
        return True

    def members(self, role: str | None = None) -> list[tuple[str, int]]:
        with self._lock:
            return sorted(k for k in self._beats
                          if k not in self._down
                          and (role is None or k[0] == role))

    # ---- health ----

    def stale(self) -> list[tuple[str, int, float]]:
        """(role, id, seconds-silent) for members past the staleness bar.
        Newly stale members emit ONE ``cluster.stale`` instant each (the
        episode ends when the member beats again); every call refreshes
        the stale-members gauge."""
        now = self._clock()
        bar = self.settings.stale_after_s
        newly: list[tuple[str, int, float]] = []
        with self._lock:
            out = sorted(
                (r, c, now - t) for (r, c), t in self._beats.items()
                if (r, c) not in self._down and now - t > bar)
            for r, c, silent in out:
                if (r, c) not in self._stale_flagged:
                    self._stale_flagged.add((r, c))
                    newly.append((r, c, silent))
            counts, stale_n = self._counts_locked()
        self._push_gauges(counts, stale_n)
        for r, c, silent in newly:
            TRACER.instant("cluster.stale", role=r, id=c,
                           silent_seconds=round(silent, 3))
        return out

    def auto_down(self) -> list[tuple[str, int]]:
        """Mark members silent past ``auto_down_after_s`` as down; returns
        the newly downed set. Down members drop out of cluster_up counts
        until they beat again. Each transition emits a
        ``cluster.auto_down`` instant and drops the member from the
        ``raphtory_cluster_members`` gauge."""
        now = self._clock()
        bar = self.settings.auto_down_after_s
        newly = []
        with self._lock:
            for key, t in self._beats.items():
                if key not in self._down and now - t > bar:
                    self._down.add(key)
                    self._stale_flagged.discard(key)
                    newly.append(key)
            if newly:
                counts, stale_n = self._counts_locked()
        if newly:
            self._push_gauges(counts, stale_n)
            for r, c in sorted(newly):
                TRACER.instant("cluster.auto_down", role=r, id=c)
        return sorted(newly)

    # ---- cluster-up gate (WatchDog.scala:66-83) ----

    def cluster_up(self) -> bool:
        with self._lock:
            alive = [k for k in self._beats if k not in self._down]
            shards = sum(1 for r, _ in alive if r == "shard")
            sources = sum(1 for r, _ in alive if r == "source")
        return (shards >= self.settings.min_shards
                and sources >= self.settings.min_sources)

    def await_up(self, timeout_s: float = 60.0, poll_s: float = 0.05) -> bool:
        """Block until cluster_up (the Spout 'stateCheck' poll loop,
        SpoutTrait.scala:70-88)."""
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self.cluster_up():
                return True
            _time.sleep(poll_s)
        return self.cluster_up()

    # ---- observability snapshot (/statusz, federated by /clusterz) ----

    def status(self) -> dict:
        """Membership snapshot: live ids per role, stale members with
        silence, auto-downed members, and the cluster-up verdict."""
        now = self._clock()
        bar = self.settings.stale_after_s
        with self._lock:
            members = sorted(k for k in self._beats
                             if k not in self._down)
            down = sorted(self._down)
            stale = sorted(
                [r, c, round(now - t, 3)]
                for (r, c), t in self._beats.items()
                if (r, c) not in self._down and now - t > bar)
        by_role: dict[str, list[int]] = {}
        for r, c in members:
            by_role.setdefault(r, []).append(c)
        return {
            "cluster_up": self.cluster_up(),
            "members": by_role,
            "stale": stale,
            "down": [[r, c] for r, c in down],
        }
