"""Blockchain dataset family — Ethereum / Bitcoin / Chainalysis.

Parsers mirror the reference routers' graph shapes (behavior, not code):

* ``EthereumTransactionParser`` — 4-column csv ``from,to,txid,timestamp`` →
  wallet→wallet edge per transaction; empty ``to`` means burnt coins sent to
  the "null" wallet (``EthereumTransactionRouter.scala``). Wallet addresses
  are hashed to i64 ids (``assignID`` analogue) with the raw address kept as
  an immutable string property.
* ``BitcoinBlockParser`` — one JSON block per record → bipartite
  transaction↔address graph: a vertex per txid (``type='transaction'`` plus
  block metadata), a vertex per output address (``type='address'``), an edge
  tx→address per vout carrying ``value``; coinbase inputs come from the
  "coingen" vertex; non-coinbase inputs attach spent-output edges
  address→tx (``BitcoinRouter.scala``).
* ``ChainalysisABParser`` — csv rows ``txid,srcCluster,dstCluster,btc,usd,
  time`` → cluster→transaction→cluster with BitCoin/USD value properties on
  both legs (``ChainalysisABRouter.scala``).

Domain analysers are the core library specialised: ``EthereumTaintTracking``
(time-respecting taint over transaction occurrences, incl. the
exchange-stop variant via ``stop_list``) and ``EthereumDegreeRanking``.

The reference's live spouts (geth JSON-RPC poller, Kafka, Postgres) need
network egress; their capability surface here is a ``Source`` that reads
pre-fetched block JSON from file/iterable — the RPC pollers are thin wrappers
a deployment adds around it.
"""

from __future__ import annotations

import json

from ..algorithms.rankings import DegreeRanking
from ..algorithms.taint import TaintTracking
from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, VertexAdd, assign_id

# core algorithms under their reference example names
EthereumTaintTracking = TaintTracking   # stop_list=() ⇒ plain TaintTracking;
                                        # non-empty ⇒ TaintTrackExchangeStop
EthereumDegreeRanking = DegreeRanking


class EthereumTransactionParser(Parser):
    """``from,to,txid,timestamp`` (seconds) — reference columns 0..3."""

    def __init__(self, sep: str = ","):
        self.sep = sep

    def __call__(self, raw: str):
        f = [c.strip().strip("()") for c in raw.split(self.sep)]
        try:
            t = int(f[3]) * 1000  # seconds → millis like the reference
        except (ValueError, IndexError):
            return []
        src_addr = f[0]
        dst_addr = f[1] if len(f) > 1 and f[1] else "null"
        src = assign_id(src_addr)
        dst = assign_id(dst_addr)
        return [
            VertexAdd(t, src, {"!id": src_addr}),
            VertexAdd(t, dst, {"!id": dst_addr}),
            EdgeAdd(t, src, dst, {"!id": f[2] if len(f) > 2 else ""}),
        ]


class BitcoinBlockParser(Parser):
    """One JSON block (dict or string) → tx/address bipartite updates."""

    COINGEN = assign_id("coingen")

    def __call__(self, raw):
        try:
            return self._parse(raw)
        except (KeyError, ValueError, TypeError, AttributeError):
            return []  # malformed block: dropped, never fatal to the source

    def _parse(self, raw):
        block = json.loads(raw) if isinstance(raw, str) else raw
        t = int(block["time"])
        height = int(block.get("height", -1))
        blockhash = str(block.get("hash", ""))
        out = []
        for tx in block.get("tx", []):
            txid = str(tx["txid"])
            tx_vid = assign_id(txid)
            total = 0.0
            for vout in tx.get("vout", []):
                value = float(vout.get("value", 0.0))
                spk = vout.get("scriptPubKey", {})
                addrs = spk.get("addresses") or ["nulldata"]
                addr = str(addrs[0])
                if addr == "nulldata":
                    value = 0.0  # burnt money, like the reference
                total += value
                a_vid = assign_id(addr)
                out.append(VertexAdd(t, a_vid, {"!type": "address",
                                                "!address": addr}))
                out.append(EdgeAdd(t, tx_vid, a_vid,
                                   {"n": int(vout.get("n", 0)),
                                    "value": value}))
            out.append(VertexAdd(t, tx_vid, {
                "!type": "transaction", "!id": txid, "total": total,
                "block": height, "!blockhash": blockhash}))
            for vin in tx.get("vin", []):
                if "coinbase" in vin:
                    out.append(VertexAdd(t, self.COINGEN,
                                         {"!type": "coingen"}))
                    out.append(EdgeAdd(t, self.COINGEN, tx_vid))
                elif "txid" in vin:  # spending a previous tx's output
                    out.append(EdgeAdd(t, assign_id(str(vin["txid"])), tx_vid,
                                       {"vout": int(vin.get("vout", 0))}))
        return out


# Litecoin and Dashcoin expose Bitcoin's block-RPC JSON shape verbatim;
# the reference's LitecoinRouter / DashcoinRouter are structural twins of
# BitcoinRouter (examples/blockchain/routers/LitecoinRouter.scala,
# DashcoinRouter.scala), so one parser class serves all three chains —
# named here so each reference example resolves by its own name.
LitecoinBlockParser = BitcoinBlockParser
DashcoinBlockParser = BitcoinBlockParser


class ChainalysisABParser(Parser):
    """``txid,srcCluster,dstCluster,btc,usd,time`` → two-leg payment path."""

    def __init__(self, sep: str = ","):
        self.sep = sep

    def __call__(self, raw: str):
        f = [c.strip() for c in raw.split(self.sep)]
        try:
            t = int(f[5])
            btc = float(f[3])
            usd = float(f[4])
        except (ValueError, IndexError):
            return []
        src = assign_id("cluster:" + f[1])
        dst = assign_id("cluster:" + f[2])
        tx = assign_id("tx:" + f[0])
        val = {"BitCoin": btc, "USD": usd}
        return [
            VertexAdd(t, src, {"!type": "Cluster"}),
            VertexAdd(t, dst, {"!type": "Cluster"}),
            VertexAdd(t, tx, {"!type": "Transaction"}),
            EdgeAdd(t, src, tx, dict(val)),
            EdgeAdd(t, tx, dst, dict(val)),
        ]
