"""The synthetic stress workload as JSON commands on the wire.

The reference's ``RandomSpout`` emits JSON command strings
(``examples/random/actors/RandomSpout.scala:46-59``) that ``RandomRouter``
parses back into typed updates (``RandomRouter.scala:142-213``). The
GraphUpdate-native fast path is :class:`raphtory_tpu.ingestion.source
.RandomSource`; this module provides the wire-format pair for parity and for
exercising the parser stage under load (the paper's ramp protocol lives in
``RateLimited``).
"""

from __future__ import annotations

import json
import random

from ..ingestion.source import Source
from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, EdgeDelete, VertexAdd, VertexDelete

_PROP_KEYS = [f"prop{i}" for i in range(20)]  # 20-key pool (paper §6.1)


class RandomCommandSource(Source):
    """Yields reference-shaped JSON command strings.

    ``mix`` = (vertex-add, edge-add, vertex-del, edge-del) probabilities;
    add-only default 30/70 mirrors ``RandomSpout.distribution()``; the
    worst-case mix from the paper is (0.3, 0.4, 0.1, 0.2).
    """

    def __init__(self, n_events: int, id_pool: int = 1_000_000, seed: int = 0,
                 mix=(0.3, 0.7, 0.0, 0.0), n_props: int = 2,
                 name: str = "random-json"):
        self.n_events = n_events
        self.id_pool = id_pool
        self.seed = seed
        self.mix = mix
        self.n_props = n_props
        self.name = name
        self.disorder = 0

    def __iter__(self):
        rng = random.Random(self.seed)
        cum = []
        acc = 0.0
        for p in self.mix:
            acc += p
            cum.append(acc)
        for t in range(1, self.n_events + 1):
            r = rng.random() * cum[-1]
            src = rng.randrange(self.id_pool)
            if r <= cum[0]:
                props = {k: round(rng.random(), 6)
                         for k in rng.sample(_PROP_KEYS, self.n_props)}
                yield json.dumps({"VertexAdd": {
                    "messageID": t, "srcID": src, "properties": props}})
            elif r <= cum[1]:
                dst = rng.randrange(self.id_pool)
                yield json.dumps({"EdgeAdd": {
                    "messageID": t, "srcID": src, "dstID": dst}})
            elif r <= cum[2]:
                yield json.dumps({"VertexRemoval": {
                    "messageID": t, "srcID": src}})
            else:
                dst = rng.randrange(self.id_pool)
                yield json.dumps({"EdgeRemoval": {
                    "messageID": t, "srcID": src, "dstID": dst}})


class RandomJsonParser(Parser):
    """Parses the command JSON back into typed updates (RandomRouter parity:
    VertexAdd/EdgeAdd/VertexRemoval/EdgeRemoval keyed objects with
    messageID/srcID/dstID/properties fields)."""

    def __call__(self, raw: str):
        try:
            obj = json.loads(raw)
        except ValueError:
            return []  # reference prints unparseable commands and moves on
        if not isinstance(obj, dict):
            return []
        try:
            if "VertexAdd" in obj:
                c = obj["VertexAdd"]
                return [VertexAdd(int(c["messageID"]), int(c["srcID"]),
                                  c.get("properties") or None)]
            if "EdgeAdd" in obj:
                c = obj["EdgeAdd"]
                return [EdgeAdd(int(c["messageID"]), int(c["srcID"]),
                                int(c["dstID"]), c.get("properties") or None)]
            if "VertexRemoval" in obj:
                c = obj["VertexRemoval"]
                return [VertexDelete(int(c["messageID"]), int(c["srcID"]))]
            if "EdgeRemoval" in obj:
                c = obj["EdgeRemoval"]
                return [EdgeDelete(int(c["messageID"]), int(c["srcID"]),
                                   int(c["dstID"]))]
        except (KeyError, ValueError, TypeError):
            pass
        return []  # unknown/malformed command: reference prints and drops
