"""LDBC SNB interactive dataset — the benchmark workload with deletions.

Mirrors ``LDBCRouter.scala:15-44``: pipe-separated rows whose first column
selects the record type; ``person`` rows add (and optionally delete) a person
vertex, ``person_knows_person`` rows add (and optionally delete) a knows
edge. Column 1 is the creation timestamp, column 2 the deletion timestamp;
person ids live in column 3 (and 4 for edges) and are hashed under a
"person" prefix like the reference's ``assignID("person"+id)``.
"""

from __future__ import annotations

import datetime as _dt

from ..ingestion.parser import Parser
from ..ingestion.updates import (
    EdgeAdd,
    EdgeDelete,
    VertexAdd,
    VertexDelete,
    assign_id,
)


def _epoch_ms(ts: str) -> int:
    """ISO-8601 with offset ('2012-11-01T09:28:01.185+00:00') → unix ms."""
    return int(_dt.datetime.fromisoformat(ts.strip()).timestamp() * 1000)


class LDBCParser(Parser):
    """``vertex_deletion``/``edge_deletion`` mirror the reference env flags
    ``LDBC_VERTEX_DELETION``/``LDBC_EDGE_DELETION`` (off by default)."""

    def __init__(self, vertex_deletion: bool = False,
                 edge_deletion: bool = False, sep: str = "|"):
        self.vertex_deletion = vertex_deletion
        self.edge_deletion = edge_deletion
        self.sep = sep

    def __call__(self, raw: str):
        f = raw.rstrip("\n").split(self.sep)
        if len(f) < 4:
            return []
        kind = f[0]
        try:
            created = _epoch_ms(f[1])
        except ValueError:
            return []
        # the deletion column is only parsed when a deletion flag asks for
        # it — rows with empty/odd deletion dates must still ADD normally
        if kind == "person":
            vid = assign_id("person" + f[3])
            out = [VertexAdd(created, vid, {"!type": "person"})]
            if self.vertex_deletion:
                try:
                    out.append(VertexDelete(_epoch_ms(f[2]), vid))
                except ValueError:
                    pass
            return out
        if kind == "person_knows_person" and len(f) >= 5:
            src = assign_id("person" + f[3])
            dst = assign_id("person" + f[4])
            out = [EdgeAdd(created, src, dst)]
            if self.edge_deletion:
                try:
                    out.append(EdgeDelete(_epoch_ms(f[2]), src, dst))
                except ValueError:
                    pass
            return out
        return []
