"""Gab social-network dataset (the README demo workload).

Parsers mirror the two reference routers over the semicolon-separated Gab
dump: user↔user reply edges (``GabUserGraphRouter.scala:20-35`` — columns 2
and 5, rows with non-positive parent dropped) and post→post comment edges
(``GabPostGraphRouter`` — columns 1 and 4). ``GabMostUsedTopics`` is the
domain analyser (``examples/gab/analysis/GabMostUsedTopics.scala``): top-k
topic vertices by in-degree with their string id/title properties.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
from dataclasses import dataclass

import numpy as np

from ..algorithms.rankings import DegreeRanking
from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, VertexAdd, assign_id


def _epoch(ts: str) -> int:
    """'2016-08-10 13:58:06(.frac)' or ISO-T variant → unix seconds (UTC),
    like the reference's dateToUnixTime over the first 19 chars. Already-
    numeric timestamps (pre-converted dumps) pass through unchanged."""
    s = ts.strip()
    try:
        return int(s)
    except ValueError:
        pass
    d = _dt.datetime.strptime(s[:19].replace("T", " "), "%Y-%m-%d %H:%M:%S")
    return int(d.replace(tzinfo=_dt.timezone.utc).timestamp())


class GabUserGraphParser(Parser):
    """user→parent-user reply edges; drops rows whose parent id <= 0."""

    def __init__(self, sep: str = ";", time_col: int = 0, src_col: int = 2,
                 dst_col: int = 5):
        self.sep = sep
        self.time_col = time_col
        self.src_col = src_col
        self.dst_col = dst_col

    def __call__(self, raw: str):
        f = [c.strip() for c in raw.split(self.sep)]
        try:
            src = int(f[self.src_col])
            dst = int(f[self.dst_col])
            if dst <= 0:
                return []
            t = _epoch(f[self.time_col])
        except (ValueError, IndexError):
            return []
        return [
            VertexAdd(t, src, {"!type": "User"}),
            VertexAdd(t, dst, {"!type": "User"}),
            EdgeAdd(t, src, dst),
        ]


class GabPostGraphParser(GabUserGraphParser):
    """post→parent-post comment edges (the commented-out 'comment wise'
    column choice in the reference router: columns 1 and 4)."""

    def __init__(self, sep: str = ";", time_col: int = 0, src_col: int = 1,
                 dst_col: int = 4):
        super().__init__(sep, time_col, src_col, dst_col)


class GabRawPostParser(Parser):
    """Deep raw-Gab JSON model: one JSON post object per line, unfolded
    into the reference's heterogeneous graph (``GabRawRouter.scala:28-130``
    over the ``rawgraphmodel/GabPost.scala`` case-class tree):

    * the post vertex carries ``user``/``likeCount``/``score``/``topic``
      string props and ``type=post``;
    * the author becomes a ``type=user`` vertex (id/name/username/verified
      props) with ``userToPost`` AND ``postToUser`` edges;
    * the topic becomes a ``type=topic`` vertex (id/title/category/
      created_at props) with a ``postToTopic`` edge;
    * a quoted/replied parent post unfolds ONE level (the reference's
      single-recursion guard) plus a ``childToParent`` edge — emitted
      at the CHILD's timestamp, child→parent (deliberate deviation: the
      reference stamps it with the parent's earlier time and inverted
      endpoints, ``GabRawRouter.scala:118-121``, which makes the child
      vertex exist before it was posted).

    Ids are namespaced blake2b hashes (``assign_id``) instead of the
    reference's clash-prone ``"user".hashCode + id`` / ``2^24 + hash``
    scheme; unparseable lines are dropped (counted by the pipeline), like
    the router's catch-all."""

    NULL = "null"

    def __call__(self, raw: str):
        try:
            post = _json.loads(raw)
            if not isinstance(post, dict):
                return []
            return self._unfold(post, child=None)
        except (ValueError, KeyError, TypeError, OverflowError,
                AttributeError):
            return []   # "Could not parse post"

    def _unfold(self, post: dict, child: tuple | None):
        """``child``: (child_vid, child_time) when this dict is a parent
        being unfolded from its reply."""
        t = _epoch(str(post["created_at"])[:19])
        vid = assign_id(f"gab:post:{int(post['id'])}")
        user = post.get("user")
        user = user if isinstance(user, dict) else None
        topic = post.get("topic")
        topic = topic if isinstance(topic, dict) else None

        def s(v):
            return self.NULL if v is None else str(v)

        out = [VertexAdd(t, vid, {
            "user": s((user or {}).get("id")),
            "likeCount": s(post.get("like_count")),
            "score": s(post.get("score")),
            "topic": s((topic or {}).get("id")),
            "!type": "post",
        })]
        if user is not None:
            uvid = assign_id(f"gab:user:{int(user['id'])}")
            out.append(VertexAdd(t, uvid, {
                "!type": "user",
                "id": s(user.get("id")),
                "name": s(user.get("name")),
                "username": s(user.get("username")),
                "verified": s(user.get("verified")),
            }))
            out.append(EdgeAdd(t, uvid, vid, {"!type": "userToPost"}))
            out.append(EdgeAdd(t, vid, uvid, {"!type": "postToUser"}))
        if topic is not None and topic.get("id") is not None:
            tvid = assign_id(f"gab:topic:{topic['id']}")
            out.append(VertexAdd(t, tvid, {
                "created_at": s(topic.get("created_at")),
                "category": s(topic.get("category")),
                "title": s(topic.get("title")),
                "!type": "topic",
                "id": s(topic.get("id")),
            }))
            out.append(EdgeAdd(t, vid, tvid, {"!type": "postToTopic"}))
        if child is not None:
            child_vid, child_t = child
            out.append(EdgeAdd(child_t, child_vid, vid,
                               {"!type": "childToParent"}))
        parent = post.get("parent")
        if isinstance(parent, dict) and child is None:   # one level only
            out.extend(self._unfold(parent, child=(vid, t)))
        return out


@dataclass(frozen=True)
class GabMostUsedTopics(DegreeRanking):
    """Top-k vertices of string-type ``topic`` by in-degree, reporting their
    ``id``/``title`` string properties — a host reducer over one device
    in-degree pass (the reference runs it as a 1-superstep analyser)."""

    top_k: int = 10
    by: str = "in"
    type_prop: str = "type"
    type_value: str = "topic"

    def reduce(self, result, view, window=None):
        ind = np.asarray(result["in"])
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        vtype = view.vertex_prop_str(self.type_prop)
        is_topic = mask & np.array(
            [v == self.type_value for v in vtype], bool)
        score = np.where(is_topic, ind, -1)
        order = np.argsort(-score, kind="stable")[: self.top_k]
        ids = view.vertex_prop_str("id")
        titles = view.vertex_prop_str("title")
        return {
            "topics": [
                {
                    "id": ids[i] if ids[i] is not None else str(int(view.vids[i])),
                    "title": titles[i] if titles[i] is not None else "no title",
                    "uses": int(ind[i]),
                }
                for i in order
                if is_topic[i]
            ]
        }
