"""Gab social-network dataset (the README demo workload).

Parsers mirror the two reference routers over the semicolon-separated Gab
dump: user↔user reply edges (``GabUserGraphRouter.scala:20-35`` — columns 2
and 5, rows with non-positive parent dropped) and post→post comment edges
(``GabPostGraphRouter`` — columns 1 and 4). ``GabMostUsedTopics`` is the
domain analyser (``examples/gab/analysis/GabMostUsedTopics.scala``): top-k
topic vertices by in-degree with their string id/title properties.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass

import numpy as np

from ..algorithms.rankings import DegreeRanking
from ..engine.program import Context
from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, VertexAdd


def _epoch(ts: str) -> int:
    """'2016-08-10 13:58:06(.frac)' or ISO-T variant → unix seconds (UTC),
    like the reference's dateToUnixTime over the first 19 chars. Already-
    numeric timestamps (pre-converted dumps) pass through unchanged."""
    s = ts.strip()
    try:
        return int(s)
    except ValueError:
        pass
    d = _dt.datetime.strptime(s[:19].replace("T", " "), "%Y-%m-%d %H:%M:%S")
    return int(d.replace(tzinfo=_dt.timezone.utc).timestamp())


class GabUserGraphParser(Parser):
    """user→parent-user reply edges; drops rows whose parent id <= 0."""

    def __init__(self, sep: str = ";", time_col: int = 0, src_col: int = 2,
                 dst_col: int = 5):
        self.sep = sep
        self.time_col = time_col
        self.src_col = src_col
        self.dst_col = dst_col

    def __call__(self, raw: str):
        f = [c.strip() for c in raw.split(self.sep)]
        try:
            src = int(f[self.src_col])
            dst = int(f[self.dst_col])
            if dst <= 0:
                return []
            t = _epoch(f[self.time_col])
        except (ValueError, IndexError):
            return []
        return [
            VertexAdd(t, src, {"!type": "User"}),
            VertexAdd(t, dst, {"!type": "User"}),
            EdgeAdd(t, src, dst),
        ]


class GabPostGraphParser(GabUserGraphParser):
    """post→parent-post comment edges (the commented-out 'comment wise'
    column choice in the reference router: columns 1 and 4)."""

    def __init__(self, sep: str = ";", time_col: int = 0, src_col: int = 1,
                 dst_col: int = 4):
        super().__init__(sep, time_col, src_col, dst_col)


@dataclass(frozen=True)
class GabMostUsedTopics(DegreeRanking):
    """Top-k vertices of string-type ``topic`` by in-degree, reporting their
    ``id``/``title`` string properties — a host reducer over one device
    in-degree pass (the reference runs it as a 1-superstep analyser)."""

    top_k: int = 10
    by: str = "in"
    type_prop: str = "type"
    type_value: str = "topic"

    def reduce(self, result, view, window=None):
        ind = np.asarray(result["in"])
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        vtype = view.vertex_prop_str(self.type_prop)
        is_topic = mask & np.array(
            [v == self.type_value for v in vtype], bool)
        score = np.where(is_topic, ind, -1)
        order = np.argsort(-score, kind="stable")[: self.top_k]
        ids = view.vertex_prop_str("id")
        titles = view.vertex_prop_str("title")
        return {
            "topics": [
                {
                    "id": ids[i] if ids[i] is not None else str(int(view.vids[i])),
                    "title": titles[i] if titles[i] is not None else "no title",
                    "uses": int(ind[i]),
                }
                for i in order
                if is_topic[i]
            ]
        }
