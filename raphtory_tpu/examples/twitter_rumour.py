"""Twitter rumour-interaction dataset (PHEME-style).

Mirrors ``rumourInteractRouter.scala``: each record is a rumour status tag
plus one tweet JSON object; a tweet replying to someone becomes a reply edge
user→replied-to-user stamped with the IMMUTABLE ``rumourStatus`` property
(first write wins — ``ImmutableProperty.scala:9-11``); a non-reply tweet
becomes a lone vertex with the same property. Records may be pre-joined
strings ``"<status>__<tweet-json>"`` (the reference packs a status and a
file path this way) or ``(status, json)`` tuples.
"""

from __future__ import annotations

import datetime as _dt
import json

from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, VertexAdd

_TWITTER_FMT = "%a %b %d %H:%M:%S %z %Y"   # EEE MMM dd HH:mm:ss ZZZZZ yyyy


def _twitter_epoch_ms(date: str) -> int:
    return int(_dt.datetime.strptime(date.strip(), _TWITTER_FMT)
               .timestamp() * 1000)


class RumourParser(Parser):
    def __call__(self, raw):
        # any malformed record is dropped, never fatal — one bad line must
        # not kill the source (the reference prints and moves on)
        try:
            if isinstance(raw, tuple):
                status, payload = raw
            else:
                status, payload = str(raw).split("__", 1)
            tweet = json.loads(payload) if isinstance(payload, str) else payload
            t = _twitter_epoch_ms(tweet["created_at"])
            src = int(tweet["user"]["id"])
            reply_to = tweet.get("in_reply_to_user_id")
            props = {"!rumourStatus": str(status)}
            if reply_to is not None:
                return [EdgeAdd(t, src, int(reply_to), props)]
            return [VertexAdd(t, src, props)]
        except (KeyError, ValueError, TypeError):
            return []
