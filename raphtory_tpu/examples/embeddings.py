"""Temporal vertex embeddings — a capability BEYOND the reference.

The reference's analysers push scalars through actor mailboxes
(``Analyser.scala:30-63``); it has no representation-learning surface at
all. This example derives unsupervised structural embeddings over a
temporal window by propagating random features through the windowed graph
(``engine/features.py`` — GraphSAGE-mean shape) and exposes the two
queries people actually run on embeddings: nearest neighbours and
drift-over-time (how much a vertex's neighbourhood changed between two
windows — rumour/anomaly surfacing on the Gab or Twitter domains).
"""

from __future__ import annotations

import numpy as np

from ..core.events import EventLog
from ..engine.device_sweep import DeviceSweep
from ..engine.features import FeatureAggregator


class TemporalEmbeddings:
    """Windowed structural embeddings over a pinned log.

    Ascending query times ride one incremental device sweep; a backward
    query transparently rebuilds the sweep (full re-fold + re-upload — fine
    for exploration, expensive in a tight loop)."""

    def __init__(self, log: EventLog, dim: int = 64, rounds: int = 2,
                 seed: int = 0):
        self._log = log
        self._dim = dim
        self._seed = seed
        self.rounds = rounds
        self._fresh()

    def _fresh(self) -> None:
        self.ds = DeviceSweep(self._log)
        self.fa = FeatureAggregator(self.ds, feature_dim=self._dim)
        self._X = self.fa.random_features(seed=self._seed)

    def at(self, time: int, window: int | None = None) -> np.ndarray:
        """[n, dim] embeddings at `time` (rows follow ``self.ds.uv``)."""
        if self.ds.t_now is not None and int(time) < self.ds.t_now:
            self._fresh()   # backward in history: rebuild the sweep
        H = self.fa.propagate(self._X, int(time), window=window,
                              rounds=self.rounds)
        return np.asarray(H)[: self.ds.n]

    def _window_alive(self, window: int | None) -> np.ndarray:
        """bool[n]: in-view (and in-window) vertices at the sweep's time —
        dead or not-yet-born vertices keep their random init rows and must
        not pollute similarity rankings."""
        sw = self.ds.sw
        alive = sw.v_alive.copy()
        if window is not None:
            alive &= sw.v_lat >= self.ds.t_now - int(window)
        return alive

    def nearest(self, vid: int, time: int, window: int | None = None,
                k: int = 5) -> list[tuple[int, float]]:
        """k most similar IN-WINDOW vertices to `vid` by cosine."""
        H = self.at(time, window)
        i = int(np.searchsorted(self.ds.uv, vid))
        if i >= len(self.ds.uv) or self.ds.uv[i] != vid:
            raise KeyError(f"unknown vertex {vid}")
        sims = H @ H[i]
        sims = np.where(self._window_alive(window), sims, -np.inf)
        order = np.argsort(-sims)
        out = []
        for j in order:
            if j != i and np.isfinite(sims[j]) and len(out) < k:
                out.append((int(self.ds.uv[j]), float(sims[j])))
        return out

    def drift(self, t0: int, t1: int, window: int) -> np.ndarray:
        """Per-vertex cosine distance between the [t0-window, t0] and
        [t1-window, t1] embeddings — large drift = neighbourhood changed
        (ascending t0 < t1; one incremental sweep)."""
        if t1 < t0:
            raise ValueError("drift requires t0 <= t1")
        H0 = self.at(t0, window)
        H1 = self.at(t1, window)
        sim = np.sum(H0 * H1, axis=1)
        return 1.0 - sim
