"""Track-and-trace GPS dataset → bipartite user/location visit graph.

Mirrors ``TrackAndTraceRouter.scala:10-80``: each datapoint becomes a User
vertex, a Location vertex whose id is a grid cell (lat/lon → ellipsoidal
cartesian → floor-quantised to ``grid_size`` metres → hashed), and a
"user visited location" edge. The full reference record is 25 columns
(user id col 0, lat col 4, lon col 5, epoch-seconds col 11); a compact
``user,lat,lon,time`` layout is supported for tests via column kwargs.
"""

from __future__ import annotations

import math

from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, VertexAdd, assign_id

EARTH_EQU = 6378137.0       # equatorial radius, m
EARTH_POL = 6356752.3142    # polar radius, m


def _cart(lat: float, lon: float) -> tuple[float, float]:
    e = 1 - (EARTH_EQU ** 2) / (EARTH_POL ** 2)
    n = EARTH_EQU / math.sqrt(1 - e * math.sin(lat) ** 2)
    return n * math.cos(lat) * math.cos(lon), n * math.cos(lat) * math.sin(lon)


def location_id(lat: float, lon: float, grid_size: float = 100.0) -> int:
    """Stable id of the grid cell containing (lat, lon)."""
    x, y = _cart(lat, lon)
    ptx = math.floor(x / grid_size) * grid_size
    pty = math.floor(y / grid_size) * grid_size
    return assign_id(f"{ptx}{pty}")


class TrackAndTraceParser(Parser):
    def __init__(self, grid_size: float = 100.0, sep: str = ",",
                 user_col: int = 0, lat_col: int = 4, lon_col: int = 5,
                 time_col: int = 11, time_scale: int = 1000):
        self.grid_size = grid_size
        self.sep = sep
        self.user_col = user_col
        self.lat_col = lat_col
        self.lon_col = lon_col
        self.time_col = time_col
        self.time_scale = time_scale  # seconds → millis like the reference

    def __call__(self, raw: str):
        f = [c.strip() for c in raw.split(self.sep)]
        try:
            user = int(f[self.user_col])
            lat = float(f[self.lat_col])
            lon = float(f[self.lon_col])
            t = int(f[self.time_col]) * self.time_scale
        except (ValueError, IndexError):
            return []
        loc = location_id(lat, lon, self.grid_size)
        return [
            VertexAdd(t, user, {"!type": "User"}),
            VertexAdd(t, loc, {"!type": "Location",
                               "latitude": lat, "longitude": lon}),
            EdgeAdd(t, user, loc, {"!type": "User Visited Location"}),
        ]
