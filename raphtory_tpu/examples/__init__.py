"""User-space examples — capability parity with the reference's
``SRC/examples/`` tree (SURVEY §2.8): one module per domain, each exposing
Source/Parser pairs (the reference's Spout/Router split) and the domain
analysers built on the core algorithm library.

| Reference domain | Module |
|---|---|
| ``examples/random``          | :mod:`.random_graph` |
| ``examples/gab``             | :mod:`.gab` |
| ``examples/blockchain``      | :mod:`.blockchain` |
| ``examples/ldbc``            | :mod:`.ldbc` |
| ``examples/citationNetwork`` | :mod:`.citations` |
| ``examples/trackAndTrace``   | :mod:`.track_and_trace` |
| ``examples/twitterRumour``   | :mod:`.twitter_rumour` |

Plus :mod:`.embeddings` — temporal vertex embeddings over windowed feature
propagation, a workload class the reference has no analogue for.
"""

from .blockchain import (
    BitcoinBlockParser,
    ChainalysisABParser,
    DashcoinBlockParser,
    EthereumDegreeRanking,
    EthereumTaintTracking,
    EthereumTransactionParser,
    LitecoinBlockParser,
)
from .citations import CitationParser
from .embeddings import TemporalEmbeddings
from .gab import (GabMostUsedTopics, GabPostGraphParser,
                  GabRawPostParser, GabUserGraphParser)
from .ldbc import LDBCParser
from .random_graph import RandomCommandSource, RandomJsonParser
from .track_and_trace import TrackAndTraceParser, location_id
from .twitter_rumour import RumourParser

__all__ = [
    "RandomCommandSource",
    "RandomJsonParser",
    "GabRawPostParser",
    "GabUserGraphParser",
    "GabPostGraphParser",
    "GabMostUsedTopics",
    "LitecoinBlockParser",
    "EthereumTransactionParser",
    "EthereumTaintTracking",
    "EthereumDegreeRanking",
    "BitcoinBlockParser",
    "ChainalysisABParser",
    "DashcoinBlockParser",
    "LDBCParser",
    "CitationParser",
    "TrackAndTraceParser",
    "location_id",
    "RumourParser",
]
