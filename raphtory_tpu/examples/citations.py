"""Citation-network dataset.

Mirrors ``CitationRouter.scala``: csv rows
``source,target,sourceCitedTargetOn,targetCreationDate,targetLastCitedOn``
(dates ``dd/MM/yyyy`` → unix seconds). The source vertex appears at citation
time, the target at its creation date, the citation edge at citation time —
and when this citation is the target's LAST one, the edge is tombstoned at
that same time (the reference's quirky end-of-life signal)."""

from __future__ import annotations

import datetime as _dt

from ..ingestion.parser import Parser
from ..ingestion.updates import EdgeAdd, EdgeDelete, VertexAdd


def _epoch(d: str) -> int:
    dt = _dt.datetime.strptime(d.strip(), "%d/%m/%Y")
    return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp())


class CitationParser(Parser):
    def __init__(self, sep: str = ","):
        self.sep = sep

    def __call__(self, raw: str):
        f = [c.strip() for c in raw.split(self.sep)]
        try:
            src = int(f[0])
            dst = int(f[1])
            cited_on = _epoch(f[2])
            target_created = _epoch(f[3])
            last_cited = _epoch(f[4])
        except (ValueError, IndexError):
            return []
        out = [
            VertexAdd(cited_on, src),
            VertexAdd(target_created, dst),
            EdgeAdd(cited_on, src, dst),
        ]
        if cited_on == last_cited:
            out.append(EdgeDelete(last_cited, src, dst))
        return out
