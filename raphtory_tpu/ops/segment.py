"""Segment combiners — the TPU-native replacement for vertex message passing.

The reference delivers typed point-to-point actor messages per vertex
(``VertexVisitor.scala:99-161`` → ``ReaderWorker.scala:137-157`` appending to
``VertexMutliQueue``). Here, a superstep's messages are a flat per-edge payload
array combined at the destination with an associative-commutative reduction —
one fused gather/segment-reduce the XLA scheduler can tile, instead of 2M-deep
actor mailboxes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEUTRAL = {
    "sum": lambda dt: jnp.zeros((), dt),
    "min": lambda dt: (jnp.array(jnp.iinfo(dt).max, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(jnp.inf, dt)),
    "max": lambda dt: (jnp.array(jnp.iinfo(dt).min, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(-jnp.inf, dt)),
}

_SEG = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def neutral(op: str, dtype) -> jnp.ndarray:
    return _NEUTRAL[op](jnp.dtype(dtype))


def segment_combine(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    op: str,
    mask: jnp.ndarray | None = None,
    indices_are_sorted: bool = True,
):
    """Combine per-edge payloads at their destination vertex.

    `data` may have trailing feature dims; `mask` rows are replaced with the
    combiner's neutral element so padded edges are no-ops. `indices_are_sorted`
    may only be True when ids are sorted INCLUDING padding rows — the snapshot
    builder pads e_dst with n_pad-1 (the max id) to preserve the promise.
    """
    if op not in _SEG:
        raise ValueError(f"unknown combiner {op!r}; use one of {sorted(_SEG)}")
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
        data = jnp.where(m, data, neutral(op, data.dtype))
    return _SEG[op](
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )
