"""Segment combiners — the TPU-native replacement for vertex message passing.

The reference delivers typed point-to-point actor messages per vertex
(``VertexVisitor.scala:99-161`` → ``ReaderWorker.scala:137-157`` appending to
``VertexMutliQueue``). Here, a superstep's messages are a flat per-edge payload
array combined at the destination with an associative-commutative reduction —
one fused gather/segment-reduce the XLA scheduler can tile, instead of 2M-deep
actor mailboxes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEUTRAL = {
    "sum": lambda dt: jnp.zeros((), dt),
    "min": lambda dt: (jnp.array(jnp.iinfo(dt).max, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(jnp.inf, dt)),
    "max": lambda dt: (jnp.array(jnp.iinfo(dt).min, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(-jnp.inf, dt)),
}

_SEG = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def neutral(op: str, dtype) -> jnp.ndarray:
    return _NEUTRAL[op](jnp.dtype(dtype))


def segment_combine(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    op: str,
    mask: jnp.ndarray | None = None,
    indices_are_sorted: bool = True,
):
    """Combine per-edge payloads at their destination vertex.

    `data` may have trailing feature dims; `mask` rows are replaced with the
    combiner's neutral element so padded edges are no-ops. `indices_are_sorted`
    may only be True when ids are sorted INCLUDING padding rows — the snapshot
    builder pads e_dst with n_pad-1 (the max id) to preserve the promise.
    """
    if op not in _SEG:
        raise ValueError(f"unknown combiner {op!r}; use one of {sorted(_SEG)}")
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
        data = jnp.where(m, data, neutral(op, data.dtype))
    return _SEG[op](
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def segment_sum_sorted_csr(
    data: jnp.ndarray,
    segment_ids_sorted: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
    block_size: int | None = None,
):
    """Sum combine over SORTED segment ids via a SEGMENTED prefix scan +
    boundary gathers — the TPU-native replacement for scatter-add on the
    hot path (XLA's scatter lowering costs ~3x a scan per element on TPU;
    measured, tools/tpu_physics.py).

    The scan carries (started, running_sum) and RESETS at every segment
    start, so sums accumulate at each segment's own magnitude — unlike a
    global cumsum-and-difference, whose absolute error floor is
    ulp(running total) and which would drown per-vertex sums at
    multi-million-segment scale. The per-segment result is the scanned
    value at the segment's last row (one small gather at indptr[j+1]-1).

    ``block_size``: when the flat array is a stack of independent blocks
    (the engine's window-major layout, segments never spanning blocks), the
    scan runs per block along axis 1 — the scan tree over a block is then
    identical to a single-block run, keeping batched results bitwise equal
    to unbatched ones."""
    if mask is not None:
        mk = mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
        data = jnp.where(mk, data, jnp.zeros((), data.dtype))
    m = len(data)
    b = block_size if block_size is not None else m
    k = m // b
    ids2 = segment_ids_sorted.reshape(k, b)
    starts = jnp.concatenate(
        [jnp.ones((k, 1), bool), ids2[:, 1:] != ids2[:, :-1]], axis=1)

    def op(a, c):
        af, av = a
        cf, cv = c
        return (af | cf, jnp.where(
            cf.reshape(cf.shape + (1,) * (av.ndim - cf.ndim)), cv, av + cv))

    data2 = data.reshape((k, b) + data.shape[1:])
    _, scanned = jax.lax.associative_scan(op, (starts, data2), axis=1)
    scanned = scanned.reshape((m,) + data.shape[1:])
    # CSR boundaries from the sorted ids themselves (one vectorised
    # searchsorted — no host-built indptr to ship); empty segments -> 0
    indptr = jnp.searchsorted(
        segment_ids_sorted, jnp.arange(num_segments + 1, dtype=jnp.int32))
    last = jnp.clip(indptr[1:] - 1, 0, m - 1)
    out = scanned[last]
    nonempty = indptr[1:] > indptr[:-1]
    return jnp.where(
        nonempty.reshape(nonempty.shape + (1,) * (out.ndim - 1)),
        out, jnp.zeros((), out.dtype))


def partition_segment_reduce(
    data: jnp.ndarray,
    local_ids: jnp.ndarray,
    n_per: int,
    num_segments: int,
    op: str = "sum",
    mask: jnp.ndarray | None = None,
):
    """Partition-blocked segment reduction — the PCPM combine primitive
    (``ops/partition.py``; docs/KERNELS.md).

    ``data`` is ``[P, cap, ...]`` destination-binned edge payloads and
    ``local_ids`` ``[P, cap]`` the in-partition destination rows
    (``dst - p * n_per``). Each partition reduces into its own DENSE
    ``n_per``-row block — P independent small reductions XLA can pipeline,
    each with a cache-resident accumulator slice, instead of one scatter
    whose target spans the whole vertex space. The blocks concatenate to
    ``[P * n_per, ...]`` and slice to ``num_segments`` (the last partition
    may overhang a non-dividing vertex count).

    Masked rows are replaced with the combiner's neutral element, so
    cap-padding and window-dead edges are no-ops. Sum results equal a flat
    ``segment_sum`` up to f32 reduction order; min/max are order-exact.
    """
    if op not in _SEG:
        raise ValueError(f"unknown combiner {op!r}; use one of {sorted(_SEG)}")
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
        data = jnp.where(m, data, neutral(op, data.dtype))
    P = data.shape[0]
    seg = _SEG[op]
    out = jax.vmap(
        lambda d, i: seg(d, i, num_segments=n_per))(data, local_ids)
    return out.reshape((P * n_per,) + data.shape[2:])[:num_segments]


_V_BITS = 31  # segment_mode value budget: non-negative ints < 2**31


def segment_mode(
    values: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
    default: int = -1,
):
    """Most frequent value per segment; ties break to the SMALLEST value.

    The sort-based generic-inbox path (SURVEY §7.3 "message-passing
    generality"): where the reference hands each vertex a mailbox of
    arbitrary messages (``VertexMutliQueue``), algorithms needing the full
    inbox — label histograms, majority votes — sort the flat (segment,
    value) pairs, count equal-value runs with one segment-sum, and reduce
    runs per segment with one segment-max. Three XLA ops, static shapes, no
    per-vertex loops. Values must be non-negative int32-range (< 2**31).

    Segments with no (unmasked) rows get ``default``.
    """
    m = len(values)
    v = values.astype(jnp.int64)
    s = segment_ids.astype(jnp.int64)
    # Out-of-range values would alias into neighbouring segments through the
    # packed key; park them with the masked rows so violations degrade to
    # "no message" instead of corrupting other segments' histograms.
    in_range = (v >= 0) & (v < (1 << _V_BITS))
    if mask is not None:
        in_range = in_range & mask
    s = jnp.where(in_range, s, num_segments)  # park bad rows at the end
    v = jnp.where(in_range, v, 0)
    key = (s << _V_BITS) | v
    ks = jnp.sort(key)
    ss = ks >> _V_BITS
    vs = ks & ((1 << _V_BITS) - 1)
    start = jnp.concatenate(
        [jnp.ones((1,), bool), ks[1:] != ks[:-1]])  # (seg,val) run starts
    run_id = jnp.cumsum(start) - 1
    run_len = jax.ops.segment_sum(
        jnp.ones((m,), jnp.int64), run_id, num_segments=m,
        indices_are_sorted=True)
    # one candidate per run (its start row): score = count ⊕ inverted value,
    # so segment-max = (max count, then min value)
    inv_v = ((1 << _V_BITS) - 1) - vs
    score = run_len[run_id] * (1 << _V_BITS) + inv_v
    score = jnp.where(start, score, -1)
    seg_of_row = jnp.where(ss < num_segments, ss, num_segments)
    best = jax.ops.segment_max(
        score, seg_of_row, num_segments=num_segments + 1,
        indices_are_sorted=True)[:num_segments]
    val = ((1 << _V_BITS) - 1) - (best & ((1 << _V_BITS) - 1))
    return jnp.where(best > 0, val, default).astype(values.dtype)
