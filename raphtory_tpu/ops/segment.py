"""Segment combiners — the TPU-native replacement for vertex message passing.

The reference delivers typed point-to-point actor messages per vertex
(``VertexVisitor.scala:99-161`` → ``ReaderWorker.scala:137-157`` appending to
``VertexMutliQueue``). Here, a superstep's messages are a flat per-edge payload
array combined at the destination with an associative-commutative reduction —
one fused gather/segment-reduce the XLA scheduler can tile, instead of 2M-deep
actor mailboxes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEUTRAL = {
    "sum": lambda dt: jnp.zeros((), dt),
    "min": lambda dt: (jnp.array(jnp.iinfo(dt).max, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(jnp.inf, dt)),
    "max": lambda dt: (jnp.array(jnp.iinfo(dt).min, dt)
                       if jnp.issubdtype(dt, jnp.integer) else jnp.array(-jnp.inf, dt)),
}

_SEG = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def neutral(op: str, dtype) -> jnp.ndarray:
    return _NEUTRAL[op](jnp.dtype(dtype))


def segment_combine(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    op: str,
    mask: jnp.ndarray | None = None,
    indices_are_sorted: bool = True,
):
    """Combine per-edge payloads at their destination vertex.

    `data` may have trailing feature dims; `mask` rows are replaced with the
    combiner's neutral element so padded edges are no-ops. `indices_are_sorted`
    may only be True when ids are sorted INCLUDING padding rows — the snapshot
    builder pads e_dst with n_pad-1 (the max id) to preserve the promise.
    """
    if op not in _SEG:
        raise ValueError(f"unknown combiner {op!r}; use one of {sorted(_SEG)}")
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim))
        data = jnp.where(m, data, neutral(op, data.dtype))
    return _SEG[op](
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


_V_BITS = 31  # segment_mode value budget: non-negative ints < 2**31


def segment_mode(
    values: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
    default: int = -1,
):
    """Most frequent value per segment; ties break to the SMALLEST value.

    The sort-based generic-inbox path (SURVEY §7.3 "message-passing
    generality"): where the reference hands each vertex a mailbox of
    arbitrary messages (``VertexMutliQueue``), algorithms needing the full
    inbox — label histograms, majority votes — sort the flat (segment,
    value) pairs, count equal-value runs with one segment-sum, and reduce
    runs per segment with one segment-max. Three XLA ops, static shapes, no
    per-vertex loops. Values must be non-negative int32-range (< 2**31).

    Segments with no (unmasked) rows get ``default``.
    """
    m = len(values)
    v = values.astype(jnp.int64)
    s = segment_ids.astype(jnp.int64)
    # Out-of-range values would alias into neighbouring segments through the
    # packed key; park them with the masked rows so violations degrade to
    # "no message" instead of corrupting other segments' histograms.
    in_range = (v >= 0) & (v < (1 << _V_BITS))
    if mask is not None:
        in_range = in_range & mask
    s = jnp.where(in_range, s, num_segments)  # park bad rows at the end
    v = jnp.where(in_range, v, 0)
    key = (s << _V_BITS) | v
    ks = jnp.sort(key)
    ss = ks >> _V_BITS
    vs = ks & ((1 << _V_BITS) - 1)
    start = jnp.concatenate(
        [jnp.ones((1,), bool), ks[1:] != ks[:-1]])  # (seg,val) run starts
    run_id = jnp.cumsum(start) - 1
    run_len = jax.ops.segment_sum(
        jnp.ones((m,), jnp.int64), run_id, num_segments=m,
        indices_are_sorted=True)
    # one candidate per run (its start row): score = count ⊕ inverted value,
    # so segment-max = (max count, then min value)
    inv_v = ((1 << _V_BITS) - 1) - vs
    score = run_len[run_id] * (1 << _V_BITS) + inv_v
    score = jnp.where(start, score, -1)
    seg_of_row = jnp.where(ss < num_segments, ss, num_segments)
    best = jax.ops.segment_max(
        score, seg_of_row, num_segments=num_segments + 1,
        indices_are_sorted=True)[:num_segments]
    val = ((1 << _V_BITS) - 1) - (best & ((1 << _V_BITS) - 1))
    return jnp.where(best > 0, val, default).astype(values.dtype)
