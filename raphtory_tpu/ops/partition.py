"""Partition-centric (PCPM) edge layout — destination-binned segments.

The resource ledger's roofline harvest (PR 6) classified the hot columnar
kernels ``hbm_bound``: their supersteps are destination-random gathers and
scatter-adds over the whole ``[n_pad, C]`` state, so every edge touches a
cache line the next edge evicts. "Accelerating PageRank using
Partition-Centric Processing" (PCPM, PAPERS.md) is the fix this module
implements: bin edges by DESTINATION PARTITION — a contiguous ``n_per``-row
slice of the dense vertex space sized so a partition's accumulator block
stays cache-resident — and combine messages from one source into a
per-partition bucket BEFORE they cross into the partition ("Node Aware
SpMV"'s aggregate-before-crossing). The scatter side then updates a
resident slice instead of streaming cache lines from HBM, and the gather
side reads each (partition, source) row ONCE instead of once per edge.

The layout is built once per (log, partition count) on the host and cached
next to the device edge tables; compiled kernels receive its arrays as
ordinary traced operands and its :class:`PartitionSpec` as part of their
``lru_cache`` key — both knobs (``RTPU_PCPM``, ``RTPU_PARTITIONS``) are
resolved at DISPATCH time and travel into every compiled-program cache key
through the spec, never read inside a cached factory (rtpulint RT001).

Within each partition, edges sort by (src, dst): the pre-aggregation
bucket reads stream sequentially, and the residual in-partition scatter
lands in the cache-resident slice. ``RTPU_PCPM=0`` keeps every kernel on
the unbinned route, bit-identical to today. Binned float reductions sum in
a different order than the (dst, src)-sorted route — integer/min-plus
results stay bitwise equal, float sums agree to reduction-order tolerance
(docs/KERNELS.md).
"""

from __future__ import annotations

import threading
import weakref
from typing import NamedTuple

import numpy as np

#: alignment of the per-partition block capacities — keeps pad overhead
#: ~0.1% instead of the up-to-2x a power-of-two pad would cost
_ALIGN = 64

#: below this padded pair count the binning overhead (layout build, extra
#: permutation gathers) dominates what locality can give back — "auto"
#: keeps tiny graphs on the unbinned route (docs/KERNELS.md "when PCPM
#: loses")
AUTO_MIN_PAIRS = 1 << 17

#: modelled last-level cache a partition's accumulator slice must fit in,
#: and the DRAM access granularity — the two constants of the traffic
#: model below (PCPM §3 uses the same shape of model)
CACHE_BYTES = 2 << 20
CACHELINE = 64

#: default floor for sparse-frontier slice buckets (slots). Small enough
#: that a near-quiescent superstep ships ~KBs; large enough that the
#: power-of-two ladder above it has only ~log2(n/floor) rungs, so the
#: collective shape set — and with it the process_allgather compile-key
#: set — stays bounded (docs/COMM.md "bucketed padding").
SPARSE_BUCKET_FLOOR = 256


def sparse_bucket_floor() -> int:
    """Resolved ``RTPU_SPARSE_BUCKETS`` (slot floor for frontier-slice
    buckets). Read HERE, at dispatch time, by the sparse comm route —
    never from inside a compiled-program cache factory (rtpulint RT001);
    the resolved bucket length reaches collective shapes as an argument."""
    import os

    try:
        v = int(os.environ.get("RTPU_SPARSE_BUCKETS", SPARSE_BUCKET_FLOOR))
    except ValueError:
        v = SPARSE_BUCKET_FLOOR
    return max(8, v)


def frontier_bucket(count: int, floor: int | None = None,
                    cap: int | None = None) -> int:
    """Bucketed capacity for a compacted frontier slice: the smallest
    power of two >= ``count``, floored at ``floor`` slots (default: the
    resolved ``RTPU_SPARSE_BUCKETS``) — the same shape-stabilising move
    as ``_ALIGN``/``PartitionSpec.cap`` for the binned exchange, applied
    to the DCN slice so every frontier size in a power-of-two band reuses
    one collective shape. ``cap`` (when given) bounds the bucket from
    above — the dense-slice size, past which padding buys nothing."""
    floor = sparse_bucket_floor() if floor is None else max(1, int(floor))
    b = floor
    while b < count:
        b <<= 1
    if cap is not None:
        b = min(b, max(int(cap), 1))
    return b


class PartitionSpec(NamedTuple):
    """Static shape descriptor of a built layout — the hashable component
    every compiled-program cache key carries (``None`` = unbinned)."""

    partitions: int   #: P — destination partitions (contiguous dst ranges)
    n_per: int        #: vertex rows per partition (ceil(n_pad / P))
    cap: int          #: binned edge slots per partition (aligned max load)
    cap_u: int        #: pre-agg bucket slots per partition (aligned max)
    preagg: bool      #: gather through per-(partition, src) buckets


class PartitionLayout:
    """Host arrays of one destination-binned layout + cached device copy.

    Flat binned edge space ``B = P * cap``; slot ``p * cap + i`` is the
    i-th edge of partition ``p`` (edges sorted (src, dst) within the
    partition, cap-padding marked invalid):

    - ``perm [B]``    binned slot → engine edge position (pads → m_pad-1)
    - ``inv [m_pad]`` engine position → binned slot (real edges only)
    - ``b_src [B]``   global src per slot (pads → n_pad-1)
    - ``b_dst [B]``   global dst per slot (pads → n_pad-1)
    - ``valid [B]``   real-edge slots
    - ``slot [B]``    pre-agg bucket per slot, global (p * cap_u + rank)
    - ``u_src [P*cap_u]`` bucket → global src (pads → n_pad-1)
    """

    def __init__(self, spec: PartitionSpec, perm, inv, b_src, b_dst,
                 valid, slot, u_src, n_pad: int, m: int):
        self.spec = spec
        self.perm = perm
        self.inv = inv
        self.b_src = b_src
        self.b_dst = b_dst
        self.valid = valid
        self.slot = slot
        self.u_src = u_src
        self.n_pad = int(n_pad)
        self.m = int(m)
        self._dev = None
        self._lock = threading.Lock()

    def device_args(self) -> tuple:
        """The layout's device operands, uploaded once (chunked + retried
        like the static edge tables) then resident: ``(b_src, b_dst,
        valid, slot, u_src, perm)``. The upload runs OUTSIDE the lock —
        holding it across ``device_put`` would stall every other
        dispatch behind a slow interconnect (the sanitizer's
        lock-across-device-boundary finding); a rare racing duplicate
        upload just gets dropped by the loser."""
        with self._lock:
            dev = self._dev
        if dev is not None:
            return dev
        from ..utils.transfer import device_put_chunked

        dev = tuple(
            device_put_chunked(a) for a in
            (self.b_src, self.b_dst, self.valid, self.slot,
             self.u_src, self.perm))
        with self._lock:
            if self._dev is None:
                self._dev = dev
            return self._dev

    def remap_positions(self, pos: np.ndarray) -> np.ndarray:
        """Engine edge positions → binned slots, preserving the INT32_MAX
        scatter-drop sentinel the padded delta lists use."""
        sentinel = np.int32(2**31 - 1)
        safe = np.clip(pos, 0, len(self.inv) - 1)
        return np.where(pos == sentinel, sentinel,
                        self.inv[safe].astype(np.int32))

    def bin_base(self, lat: np.ndarray, alive: np.ndarray):
        """Engine-order per-pair base state → binned layout (host side, one
        fancy-index each). Invalid (cap-pad) slots are forced dead so the
        kernels never need a separate validity AND."""
        lat_b = lat[self.perm]
        alive_b = alive[self.perm] & self.valid
        return lat_b, alive_b

    def bin_values(self, vals: np.ndarray) -> np.ndarray:
        """Engine-order per-pair values (e.g. SSSP weights) → binned."""
        return vals[self.perm]


class HostTables:
    """Minimal tables surface for :func:`resolve` over a bare edge table
    (engines whose own tables object dropped its host arrays, or a view's
    per-snapshot tables). ``m`` is the REAL row count — the pow2 pad tail
    must become invalid cap-pad slots, never binned edges."""

    __slots__ = ("e_src", "e_dst", "n_pad", "m", "m_pad")

    def __init__(self, e_src, e_dst, n_pad: int, m: int):
        self.e_src = np.asarray(e_src)
        self.e_dst = np.asarray(e_dst)
        self.n_pad = int(n_pad)
        self.m = int(m)
        self.m_pad = len(self.e_src)


def partition_count(n_pad: int, budget_bytes: int,
                    override: int | None = None) -> int:
    """Partitions for an ``n_pad``-row destination space: the override, or
    auto-sized so one partition's f32 accumulator slice (at a reference
    column width of 128) stays within 1/128 of the tile budget — the same
    accounting that sizes the edge tiles (``RTPU_TILE_BUDGET_MB``). For
    the default 256 MB budget that is ``n_per = 2048`` rows."""
    if override is not None and override > 0:
        return max(1, min(int(override), int(n_pad)))
    n_per = max(1024, int(budget_bytes) >> 17)
    return max(1, -(-int(n_pad) // n_per))


def build_layout(e_src: np.ndarray, e_dst: np.ndarray, n_pad: int, m: int,
                 partitions: int) -> PartitionLayout:
    """Build the destination-binned layout for an engine edge table
    (``e_src``/``e_dst`` padded ``[m_pad]``, real edges in ``[0, m)``,
    (dst, src)-sorted). O(m log m) host work, done once per (log, P)."""
    m = int(m)
    m_pad = len(e_dst)
    P = max(1, min(int(partitions), int(n_pad)))
    n_per = -(-int(n_pad) // P)
    src = e_src[:m].astype(np.int64)
    dst = e_dst[:m].astype(np.int64)
    part = dst // n_per
    # (partition, src, dst): bucket reads stream sequentially per partition
    order = np.lexsort((dst, src, part))
    counts = np.bincount(part[order], minlength=P)
    cap = int(max(_ALIGN, -(-int(counts.max(initial=0)) // _ALIGN) * _ALIGN))
    B = P * cap
    off = np.zeros(P + 1, np.int64)
    np.cumsum(counts, out=off[1:])

    part_o = np.repeat(np.arange(P, dtype=np.int64), counts)
    within = np.arange(m, dtype=np.int64) - np.repeat(off[:-1], counts)
    slots = part_o * cap + within                      # binned slot per row

    perm = np.full(B, m_pad - 1, np.int32)
    perm[slots] = order.astype(np.int32)
    inv = np.full(m_pad, B - 1, np.int32)
    inv[order] = slots.astype(np.int32)
    b_src = np.full(B, n_pad - 1, np.int32)
    b_src[slots] = src[order].astype(np.int32)
    b_dst = np.full(B, n_pad - 1, np.int32)
    b_dst[slots] = dst[order].astype(np.int32)
    valid = np.zeros(B, bool)
    valid[slots] = True

    # pre-aggregation buckets: one per (partition, src) run — the
    # (partition, src, dst) sort makes runs contiguous
    keys = part_o * (int(n_pad) + 1) + src[order]
    first = np.ones(m, bool)
    first[1:] = keys[1:] != keys[:-1]
    u_rank = np.cumsum(first) - 1                      # global unique rank
    u_per_part = np.bincount(part_o[first], minlength=P)
    u_off = np.zeros(P + 1, np.int64)
    np.cumsum(u_per_part, out=u_off[1:])
    cap_u = int(max(_ALIGN,
                    -(-int(u_per_part.max(initial=0)) // _ALIGN) * _ALIGN))
    local_rank = u_rank - u_off[part_o]                # rank within part
    slot = np.zeros(B, np.int32)
    slot[slots] = (part_o * cap_u + local_rank).astype(np.int32)
    u_src = np.full(P * cap_u, n_pad - 1, np.int32)
    u_src[(part_o[first] * cap_u + local_rank[first]).astype(np.int64)] = \
        src[order][first].astype(np.int32)

    # the buckets only pay when they are strictly fewer gather rows than
    # the edges themselves (pathological pads can invert that)
    preagg = int(first.sum()) > 0 and P * cap_u < B
    spec = PartitionSpec(P, n_per, cap, cap_u, bool(preagg))
    return PartitionLayout(spec, perm, inv, b_src, b_dst, valid, slot,
                           u_src, n_pad, m)


# ------------------------------------------------------------ resolution

#: per-owner (log / bulk graph / tables) cache of built layouts, keyed by
#: the exact table identity (m, n, P) — the same contract as the device
#: edge-table cache (pairs are never removed from a log, so equal counts
#: mean the identical deterministic table)
_LAYOUTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_LAYOUTS_LOCK = threading.Lock()


def pcpm_enabled(m_pad: int, mode: str) -> bool:
    """``RTPU_PCPM`` decision for a graph of ``m_pad`` padded pairs:
    ``"1"`` forces the binned route, ``"0"`` the unbinned one, anything
    else — ``"auto"``, unset, set-but-empty, typos — bins only past
    :data:`AUTO_MIN_PAIRS`, below which the layout overhead dominates
    (docs/KERNELS.md). Only an explicit ``"1"`` may force tiny graphs
    onto the binned route."""
    if mode == "0":
        return False
    if mode == "1":
        return True
    return int(m_pad) >= AUTO_MIN_PAIRS


def tile_budget_bytes() -> int:
    """Resolved ``RTPU_TILE_BUDGET_MB`` in bytes — the ONE parse of the
    budget knob the partition sizing shares with the edge tiling. Always
    called at dispatch time, never inside a cached factory."""
    import os

    return int(os.environ.get("RTPU_TILE_BUDGET_MB", 256)) << 20


def resolve(owner, tables, budget_bytes: int, tag: str = ""):
    """Layout for ``tables`` (GlobalTables / BulkGraph surface: ``e_src``,
    ``e_dst``, ``n_pad``, ``m``, ``m_pad``) or ``None`` when the binned
    route is off. Reads ``RTPU_PCPM`` / ``RTPU_PARTITIONS`` HERE — at
    dispatch, outside any compiled-program factory — so both knobs reach
    the program cache keys through the returned layout's spec. ``owner``
    keys the cross-engine cache (the caller's log object outlives the
    per-engine tables); ``tag`` disambiguates different edge tables of
    one owner (a view's deduped pairs vs its occurrence rows)."""
    import os

    mode = os.environ.get("RTPU_PCPM", "auto")
    if not pcpm_enabled(tables.m_pad, mode):
        return None
    if getattr(tables, "e_src", None) is None:
        return None   # host edge tables dropped (device-only surface)
    ov = os.environ.get("RTPU_PARTITIONS")
    P = partition_count(tables.n_pad, budget_bytes,
                        int(ov) if ov else None)
    key = (tag, int(tables.m), int(tables.n_pad), int(P))
    with _LAYOUTS_LOCK:
        try:
            per_owner = _LAYOUTS.get(owner)
            if per_owner is None:
                per_owner = {}
                _LAYOUTS[owner] = per_owner
        except TypeError:
            # unweakrefable or unhashable owner (eq-dataclass views):
            # build uncached — one layout per dispatch, still correct
            per_owner = None
        ent = per_owner.get(key) if per_owner is not None else None
    if ent is not None:
        return ent
    layout = build_layout(tables.e_src, tables.e_dst, tables.n_pad,
                          tables.m, P)
    if per_owner is not None:
        with _LAYOUTS_LOCK:
            ent = per_owner.setdefault(key, layout)
        return ent
    return layout


# ---------------------------------------------------------- traffic model


def edge_traffic_model(m_pad: int, C: int, n_pad: int,
                       spec: PartitionSpec | None,
                       itemsize: int = 4) -> dict:
    """Modelled DRAM bytes of ONE message-combine superstep — the
    partition-aware refinement of the ledger's locality-blind XLA
    ``bytes_accessed`` harvest (which counts logical operand bytes and so
    CANNOT see what binning changes; docs/OBSERVABILITY.md). The model is
    the PCPM paper's own accounting: a random access into an operand whose
    working set exceeds :data:`CACHE_BYTES` costs a full
    :data:`CACHELINE`; streamed and cache-resident operands cost their
    payload bytes once.

    Unbinned: every edge gathers a state row at random (all the lines the
    row spans move) and scatter-ADDS a row at random — a read-modify-
    write, so the touched lines move TWICE — over a destination working
    set that outgrows the cache. Binned (``spec``): the gather reads each
    (partition, src) bucket row once, the bucket expansion streams, and
    the scatter lands in a cache-resident ``n_per``-row slice the cache
    absorbs — the payload streams in once and the output writes back
    once.
    """
    row = C * itemsize
    state_bytes = n_pad * row

    def lines(r: int) -> int:            # DRAM bytes one random r-byte
        return -(-r // CACHELINE) * CACHELINE   # row access moves

    rand = lines(row) if state_bytes > CACHE_BYTES else row
    out = {"model": "pcpm_superstep", "columns": int(C)}
    if spec is None:
        streamed = m_pad * (2 * 4 + C)   # ids + bool mask
        # gather: m random row reads; scatter-add: m random r-m-w
        random_bytes = m_pad * rand + 2 * m_pad * rand
        out.update(random_rows=int(2 * m_pad),
                   streamed_bytes=int(streamed),
                   est_hbm_bytes=int(random_bytes + streamed))
        return out
    B = spec.partitions * spec.cap
    slice_bytes = spec.n_per * row
    # gather side: bucket fill (random into the full state) + streamed
    # expansion through the resident bucket
    u_rows = spec.partitions * spec.cap_u if spec.preagg else B
    gather_bytes = u_rows * rand + (B * row if spec.preagg else 0)
    # scatter side: the partition slice lives in cache, so the payload
    # streams in once and the accumulator writes back once
    if slice_bytes <= CACHE_BYTES:
        scatter_bytes = B * row + n_pad * row
    else:                                # partitions mis-sized: random
        scatter_bytes = 2 * B * lines(row)
    streamed = B * (2 * 4 + C)           # ids + bool mask
    out.update(random_rows=int(u_rows),
               streamed_bytes=int(streamed),
               est_hbm_bytes=int(gather_bytes + scatter_bytes + streamed))
    return out
